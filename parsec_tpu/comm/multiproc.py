"""N-process harness over the socket fabric — the ``mpiexec -np N`` analog.

Where :func:`~parsec_tpu.comm.multirank.run_multirank` runs ranks as
threads over an in-process fabric, this launcher spawns each rank as its
own OS **process**, connected by the TCP socket fabric
(:mod:`parsec_tpu.comm.socket_fabric`) — genuinely separate interpreters,
address spaces, and GILs, exactly what a multi-host DCN deployment looks
like (set ``PARSEC_TPU_HOSTS`` and launch the same entry on each host).

The body function must be *importable* (``"pkg.module:function"`` or
``"path/to/file.py:function"``) with the ``fn(ctx, rank, nranks) ->
picklable`` signature run_multirank uses.
"""

from __future__ import annotations

import os
import pickle
import shutil
import socket
import subprocess
import sys
import tempfile
from typing import Any


def _free_port_base(nranks: int) -> int:
    """A base port whose whole [base, base+nranks) range binds (probed
    port-by-port; the range cannot be reserved atomically, so callers
    still retry on a lost race)."""
    for _attempt in range(50):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
        probe.close()
        if base + nranks >= 65000:
            continue
        ok = True
        for r in range(nranks):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", base + r))
            except OSError:
                ok = False
                break
            finally:
                s.close()
        if ok:
            return base
    raise RuntimeError("no free port range found")


def run_multiproc(nranks: int, target: str, timeout: float = 180.0,
                  nb_cores: int = 0, transport: str = "socket",
                  distributed: bool = False) -> list[Any]:
    """Run ``target`` on ``nranks`` subprocess ranks; returns the per-rank
    results.  Retries once on a lost port-range race (a bind collision
    surfaces as one rank failing, or as a timeout of the survivors).

    ``transport``: ``"socket"`` (host-object payloads) or ``"device"`` —
    each rank binds one JAX device, registered payloads live
    device-resident, and GETs land directly on the consumer's device
    (:mod:`parsec_tpu.comm.device_socket`, the deployable DCN tier).

    ``distributed=True`` bootstraps ``jax.distributed`` across the ranks
    first — a coordinator on 127.0.0.1 plus per-rank process ids, the
    exact real-pod path of :func:`~parsec_tpu.comm.device_socket.
    maybe_init_distributed` (each process then sees its local chips; on
    the forced-CPU test backend, its own CPU device).

    Execution is therefore **at-least-once**: on the retry path every rank
    body runs again from scratch, so bodies with external side effects
    (files, network writes) must be idempotent or key their outputs by
    attempt.  The collision happens while the socket fabric bootstraps —
    normally before any user code runs — but a partially-connected mesh can
    have let early ranks start their bodies before the failure surfaced."""
    if transport not in ("socket", "device"):
        raise ValueError(f"unknown transport {transport!r}")
    if distributed and transport != "device":
        # _rank_main bootstraps jax.distributed on the device-transport
        # path only; silently skipping it would fail far from the cause
        raise ValueError("distributed=True requires transport='device'")
    try:
        return _run_multiproc(nranks, target, timeout, nb_cores, transport,
                              distributed)
    except (RuntimeError, TimeoutError) as e:
        if "Address already in use" not in str(e):
            raise
        return _run_multiproc(nranks, target, timeout, nb_cores, transport,
                              distributed)


def _run_multiproc(nranks: int, target: str, timeout: float,
                   nb_cores: int, transport: str = "socket",
                   distributed: bool = False) -> list[Any]:
    # one extra port for the jax.distributed coordinator when asked
    base = _free_port_base(nranks + (1 if distributed else 0))
    tmp = tempfile.mkdtemp(prefix="parsec_mp_")
    env = dict(os.environ)
    # subprocess ranks must not grab the bench TPU (or a TPU plugin that
    # admits one process only): force plain CPU interpreters.  All ranks
    # are local here, so a leftover multi-host spec must not leak in.
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PJRT_LIBRARY_PATH", None)
    env.pop("PARSEC_TPU_HOSTS", None)
    # forward the wire-path comm params the parent may have set
    # in-process (params.set) so every rank agrees on the framing — both
    # ends of a fabric must parse the same wire format (docs/COMM.md).
    # An explicit PARSEC_MCA_* in the caller's environment still wins.
    from ..core.params import params as _p
    for name in ("comm_wire_binary", "comm_get_frag_bytes",
                 "comm_get_window", "comm_socket_buf_bytes",
                 "comm_codec_pickle_fallback", "comm_bcast_tree",
                 "comm_coll_bench_bytes"):
        env.setdefault(f"PARSEC_MCA_{name}", str(_p.get(name)))
    # forward the autotuner consult knobs the same way: every rank of a
    # fabric must agree on WHETHER (and from which store) a persisted
    # tuning vector applies, or ranks would run different knob vectors.
    # lookup(), not get(): the parent may never have imported tune/
    for name in ("tune_db", "tune_db_path", "tune_adaptive"):
        p = _p.lookup(name)
        if p is not None:
            env.setdefault(f"PARSEC_MCA_{name}", str(p.value))
    env["PARSEC_MP_NRANKS"] = str(nranks)
    env["PARSEC_MP_TARGET"] = target
    env["PARSEC_MP_BASE_PORT"] = str(base)
    env["PARSEC_MP_NB_CORES"] = str(nb_cores)
    env["PARSEC_MP_TIMEOUT"] = str(timeout)
    env["PARSEC_MP_TRANSPORT"] = transport
    if distributed:
        env["PARSEC_TPU_COORDINATOR"] = f"127.0.0.1:{base + nranks}"
        env["PARSEC_TPU_NUM_PROCS"] = str(nranks)
    else:
        env.pop("PARSEC_TPU_COORDINATOR", None)
    procs: list[subprocess.Popen] = []
    logs: list[str] = []
    try:
        for r in range(nranks):
            e = dict(env)
            e["PARSEC_MP_RANK"] = str(r)
            if distributed:
                e["PARSEC_TPU_PROC_ID"] = str(r)
            e["PARSEC_MP_RESULT"] = os.path.join(tmp, f"rank{r}.pkl")
            log = os.path.join(tmp, f"rank{r}.log")
            logs.append(log)
            with open(log, "wb") as lf:
                # per-rank log FILES, not pipes: a chatty rank must never
                # block on a full pipe the parent isn't draining yet
                procs.append(subprocess.Popen(
                    [sys.executable, "-c",
                     "from parsec_tpu.comm.multiproc import _rank_main; "
                     "_rank_main()"],
                    env=e, cwd=os.getcwd(), stdout=lf,
                    stderr=subprocess.STDOUT))
        # one shared deadline, polled: the first nonzero exit kills the
        # survivors immediately (they would otherwise hang waiting for the
        # dead rank's activations until their own timeouts)
        import time as _time
        deadline = _time.monotonic() + timeout
        failed: list[int] = []
        while True:
            codes = [p.poll() for p in procs]
            failed = [r for r, c in enumerate(codes)
                      if c is not None and c != 0]
            if failed or all(c is not None for c in codes):
                break
            if _time.monotonic() > deadline:
                for q in procs:
                    q.kill()
                for q in procs:
                    q.wait()     # reap: no zombies on the timeout path
                hung = [r for r, c in enumerate(codes) if c is None]
                raise TimeoutError(
                    f"rank(s) {hung} did not finish within {timeout}s\n"
                    + _tails(logs))
            _time.sleep(0.05)
        if failed:
            for q in procs:
                q.kill()
            for q in procs:
                q.wait()
            raise RuntimeError(
                f"rank(s) {failed} failed:\n"
                + _tails([logs[r] for r in failed]))
        results: list[Any] = []
        for r in range(nranks):
            with open(os.path.join(tmp, f"rank{r}.pkl"), "rb") as f:
                results.append(pickle.load(f))
        return results
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _tails(logs: list[str], nbytes: int = 2000) -> str:
    out = []
    for log in logs:
        try:
            with open(log, "rb") as f:
                data = f.read()[-nbytes:]
            out.append(f"--- {os.path.basename(log)} ---\n"
                       + data.decode(errors="replace"))
        except OSError:
            pass
    return "\n".join(out)


def _rank_main() -> None:
    """Subprocess entry: build the socket-backed runtime and run the body."""
    # force-CPU before jax can load a TPU plugin (mirrors tests/conftest)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    import importlib
    import importlib.util

    from ..runtime.context import Context
    from .remote_dep import RemoteDepEngine
    from .socket_fabric import SocketCommEngine, SocketFabric

    transport = os.environ.get("PARSEC_MP_TRANSPORT", "socket")
    if transport == "device":
        # real-pod hook: with a coordinator configured this initializes
        # jax.distributed so the process sees its local chips
        from .device_socket import maybe_init_distributed
        maybe_init_distributed()

    rank = int(os.environ["PARSEC_MP_RANK"])
    nranks = int(os.environ["PARSEC_MP_NRANKS"])
    base = int(os.environ["PARSEC_MP_BASE_PORT"])
    nb_cores = int(os.environ["PARSEC_MP_NB_CORES"])
    timeout = float(os.environ["PARSEC_MP_TIMEOUT"])
    mod_name, fn_name = os.environ["PARSEC_MP_TARGET"].rsplit(":", 1)
    if mod_name.endswith(".py"):    # file-path form: "dir/bodies.py:fn"
        spec = importlib.util.spec_from_file_location("_mp_target", mod_name)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name)

    fabric = SocketFabric(nranks, rank, base_port=base)
    ctx = Context(nb_cores=nb_cores, nb_ranks=nranks, my_rank=rank)
    if transport == "device":
        from .device_socket import DeviceSocketCommEngine
        ce = DeviceSocketCommEngine(fabric)
    else:
        ce = SocketCommEngine(fabric)
    eng = RemoteDepEngine(ctx, ce)
    ctx.start()
    result = fn(ctx, rank, nranks)
    # context-level drain before teardown (the run_multirank discipline)
    eng.quiesce(timeout=timeout / 2)
    ctx.fini()
    with open(os.environ["PARSEC_MP_RESULT"], "wb") as f:
        pickle.dump(result, f)
