"""N-rank harness: one runtime context per rank over a shared fabric.

The rebuild's analog of the reference's oversubscribed-MPI test runs
(``mpiexec --oversubscribe -np N``, SURVEY §4): each rank is a thread owning
its own :class:`~parsec_tpu.runtime.context.Context` (rank-local scheduler,
dep table, taskpool registry) attached to the shared
:class:`~parsec_tpu.comm.engine.InprocFabric`.  The *protocol* layer —
activation messages, rendezvous GETs, propagation trees, termdet pending
actions — is exercised exactly as it would be across hosts; only the byte
transport is in-process.

Usage::

    def body(ctx, rank, nranks):
        A = TwoDimBlockCyclic("A", ..., P=nranks, myrank=rank)
        tp = build_my_ptg(A)
        ctx.add_taskpool(tp)
        ctx.wait()
        return result_visible_on(rank)

    results = run_multirank(4, body)
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from ..runtime.context import Context
from .engine import InprocFabric
from .remote_dep import RemoteDepEngine


def run_multirank(nranks: int, fn: Callable[[Context, int, int], Any],
                  nb_cores: int = 0, timeout: float = 120.0,
                  transport: str = "inproc",
                  devices: list | None = None) -> list[Any]:
    """Run ``fn(ctx, rank, nranks)`` on every rank; returns per-rank results.

    ``nb_cores=0`` ranks drive progress from ``wait()`` (the master-thread
    funneled mode) — the default for tests, deterministic and cheap.

    ``transport="device"`` attaches the device-backed engine
    (:mod:`parsec_tpu.comm.device_fabric`): rank *i* owns JAX device *i* and
    payloads move device-to-device — the configuration the driver's
    multichip dryrun certifies.
    """
    if transport == "device":
        from .device_fabric import DeviceFabric
        fabric: InprocFabric = DeviceFabric(nranks, devices)
    else:
        fabric = InprocFabric(nranks)
    results: list[Any] = [None] * nranks
    errors: list[BaseException | None] = [None] * nranks

    def rank_main(rank: int) -> None:
        ctx = Context(nb_cores=nb_cores, nb_ranks=nranks, my_rank=rank)
        eng = RemoteDepEngine(ctx, fabric.attach(rank))
        try:
            ctx.start()
            results[rank] = fn(ctx, rank, nranks)
            # context-level drain: every rank must stay responsive until the
            # whole fabric is silent (late writebacks/acks), then tear down
            eng.quiesce(timeout=timeout / 2)
            ctx.fini()
        except BaseException as e:  # surfaced to the caller below
            errors[rank] = e
            try:
                ctx.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=rank_main, args=(r,),
                                name=f"rank{r}", daemon=True)
               for r in range(nranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(f"{t.name} did not finish within {timeout}s "
                               f"(errors so far: {errors})")
    for r, e in enumerate(errors):
        if e is not None:
            raise RuntimeError(f"rank {r} failed") from e
    return results
