"""Collective-tree communication: broadcast and reduction taskpools.

"Large Scale Distributed Linear Algebra With TPUs" (arxiv 2112.09017)
operates in the regime this module targets — dense collectives over
pod-scale meshes — and T3 (arxiv 2401.16677) argues collectives must ride
the taskpool (overlappable, fragment-granular) rather than block it.  Both
shapes here are therefore emitted as plain PTG taskpools: graphcheck-clean,
schedulable and fair-shareable like any other pool, with fragment progress
interleaved by busy workers (the ``_frag_active`` gate) and the 8-byte
trace id riding every frame via ``tp._trace``.

**Broadcast** (:func:`bcast_taskpool`): one task per tree position; the
root reads its tile, every other position receives the payload from its
:func:`tree_parent` and re-serves it to its :func:`tree_children` — the
per-hop payload move is the activation layer's staged re-serve
(``remote_dep._complete_incoming``): an interior rank re-registers the
landed buffer and its children pull from *it* over credit-windowed
fragmented GETs, so root egress is O(children(root)) payload transfers
(⌈log₂ n⌉ for binomial) instead of O(n).

**Reduction** (:func:`reduce_taskpool`): leaves ship their tile up the
same tree; interior positions combine their children's partials with a
registered op (:func:`register_reduce_op`) before forwarding, so each
edge carries exactly one tile and the root applies the final combine.

Tree shapes are the activation propagation shapes (``binomial | chain |
star``, validated — an unknown kind raises
:class:`~parsec_tpu.core.params.MCAParamValueError` instead of silently
degrading).  ``comm_bcast_tree=auto`` resolves per payload class through
:func:`~parsec_tpu.comm.remote_dep.resolve_tree_kind` — the same rule
``analysis/commcheck.recommend_tree`` derives statically (docs/COMM.md).  ``redistribute_taskpool`` routes multi-consumer fan-out
through the same staging (``data_dist/redistribute.py``).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ..core.params import params as _params
from ..data.data import data_create
from .remote_dep import (TREE_KINDS, resolve_tree_kind, tree_children,
                         tree_parent)

__all__ = ["bcast_taskpool", "reduce_taskpool", "register_reduce_op",
           "reduce_op", "tree_children", "tree_parent", "TREE_KINDS",
           "resolve_tree_kind"]


def _dtt_nbytes(V: Any) -> int | None:
    """Per-tile payload hint for ``resolve_tree_kind`` under ``auto``."""
    dtt = getattr(V, "default_dtt", None)
    try:
        return int(dtt.nbytes)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# reduction op registry
# ---------------------------------------------------------------------------

# concurrency contract (analysis.runtimelint, docs/ANALYSIS.md): no
# shared mutable state beyond the reduce-op registry, which follows the
# register-at-import / read-at-build discipline (same as the codec and
# PINS registries) — registration after pools are running is unsupported,
# so the registry carries no lock.  The empty registry declares that:
# nothing here may grow cross-thread mutation without growing an entry.
_LOCK_PROTECTED = {}
_LOCK_ORDER = ()

_REDUCE_OPS: dict[str, Callable[[Any, Any], Any]] = {
    "sum": np.add,
    "prod": np.multiply,
    "max": np.maximum,
    "min": np.minimum,
}


def register_reduce_op(name: str, fn: Callable[[Any, Any], Any]) -> None:
    """Register a binary combine for :func:`reduce_taskpool` — must be
    associative and commutative: the tree applies it in position order,
    not submission order."""
    _REDUCE_OPS[name] = fn


def reduce_op(name: str) -> Callable[[Any, Any], Any]:
    fn = _REDUCE_OPS.get(name)
    if fn is None:
        raise KeyError(f"unknown reduce op {name!r}; registered: "
                       f"{sorted(_REDUCE_OPS)} (register_reduce_op)")
    return fn


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------


def _positions(V: Any, n: int | None) -> int:
    if n is not None:
        return n
    n = getattr(V, "mt", None)
    if n is None:
        raise TypeError(f"cannot infer tree size from {type(V).__name__}; "
                        f"pass n= explicitly")
    return n


def _max_children(kind: str, n: int) -> int:
    return max((len(tree_children(kind, p, n)) for p in range(n)),
               default=0)


def bcast_taskpool(V: Any, *, root: int = 0, n: int | None = None,
                   kind: str | None = None,
                   name: str = "coll_bcast") -> Any:
    """Broadcast tile ``V(root)`` into every tile ``V(p)`` for the ``n``
    tree positions, staged along a ``kind`` tree (default: the
    ``comm_bcast_tree`` MCA param).

    Position ``p`` maps to tile ``(root + p) % n`` so the root is always
    position 0; each position runs on its tile's home rank (the task
    affinity), which is what turns the PTG edges into the staged
    activation tree on a distributed collection."""
    from .. import ptg

    n = _positions(V, n)
    kind = resolve_tree_kind(
        kind, nbytes=_dtt_nbytes(V), n=n)
    if not 0 <= root < n:
        raise ValueError(f"root {root} outside [0, {n})")
    kids = _max_children(kind, n)

    def key(p: int) -> int:
        return (root + p) % n

    p_ = ptg.PTGBuilder(name, V=V, N=n, ROOT=root)
    t = p_.task("B", p=ptg.span(0, lambda g, l: g.N - 1))
    t.affinity("V", lambda g, l: (key(l.p),))
    f = t.flow("A", ptg.RW)
    f.input(data=("V", lambda g, l: (g.ROOT,)),
            guard=lambda g, l: l.p == 0)
    f.input(pred=("B", "A",
                  lambda g, l: {"p": tree_parent(kind, l.p, g.N)}),
            guard=lambda g, l: l.p > 0)
    for s in range(kids):
        f.output(succ=("B", "A",
                       lambda g, l, s=s:
                       {"p": tree_children(kind, l.p, g.N)[s]}),
                 guard=lambda g, l, s=s:
                 s < len(tree_children(kind, l.p, g.N)))
    f.output(data=("V", lambda g, l: (key(l.p),)))

    @t.body
    def body(es, task, g, l):
        pass        # pure movement: the landed copy IS the result

    return p_.build()


# ---------------------------------------------------------------------------
# reduction
# ---------------------------------------------------------------------------


def reduce_taskpool(V: Any, OUT: Any, *, op: str = "sum", root: int = 0,
                    n: int | None = None, kind: str | None = None,
                    out_key: int = 0, name: str = "coll_reduce") -> Any:
    """Combine the ``n`` tiles of ``V`` up a ``kind`` tree with ``op``;
    the root writes the final combine into ``OUT(out_key)``.

    Each position reads its own tile (flow ``L``), receives at most one
    partial per child slot (flows ``C0..Ck``), combines, and ships the
    partial to its parent (flow ``P``) — one tile per tree edge, combines
    at interior nodes."""
    from .. import ptg

    n = _positions(V, n)
    kind = resolve_tree_kind(
        kind, nbytes=_dtt_nbytes(V), n=n)
    if not 0 <= root < n:
        raise ValueError(f"root {root} outside [0, {n})")
    fn = reduce_op(op)
    kids = _max_children(kind, n)

    def key(p: int) -> int:
        return (root + p) % n

    def slot(p: int, nn: int) -> int:
        """Which child slot of its parent position ``p`` occupies."""
        return tree_children(kind, tree_parent(kind, p, nn), nn).index(p)

    p_ = ptg.PTGBuilder(name, V=V, OUT=OUT, N=n, ROOT=root)
    t = p_.task("R", p=ptg.span(0, lambda g, l: g.N - 1))
    t.affinity("V", lambda g, l: (key(l.p),))
    fl = t.flow("L", ptg.READ)
    fl.input(data=("V", lambda g, l: (key(l.p),)))
    for s in range(kids):
        fc = t.flow(f"C{s}", ptg.READ)
        fc.input(pred=("R", "P",
                       lambda g, l, s=s:
                       {"p": tree_children(kind, l.p, g.N)[s]}),
                 guard=lambda g, l, s=s:
                 s < len(tree_children(kind, l.p, g.N)))
    fp = t.flow("P", ptg.WRITE)
    for s in range(kids):
        fp.output(succ=("R", f"C{s}",
                        lambda g, l: {"p": tree_parent(kind, l.p, g.N)}),
                  guard=lambda g, l, s=s:
                  l.p > 0 and slot(l.p, g.N) == s)
    fp.output(data=("OUT", lambda g, l: (out_key,)),
              guard=lambda g, l: l.p == 0)

    @t.body
    def body(es, task, g, l):
        acc = np.array(np.asarray(task.flow_data("L").value), copy=True)
        for s in range(len(tree_children(kind, l.p, n))):
            acc = fn(acc, np.asarray(task.flow_data(f"C{s}").value))
        task.set_flow_data(
            "P", data_create(acc, key=(name, "partial", l.p)).get_copy(0))

    return p_.build()


# ---------------------------------------------------------------------------
# multiproc bodies (bench.py comm_ranks sweep + the 8-rank acceptance test)
# ---------------------------------------------------------------------------


def _mp_collective_body(ctx, rank, nranks):
    """One broadcast of a ``comm_coll_bench_bytes`` tile + one tree
    reduction, timed; returns per-rank latency, payload digests, and the
    socket fabric's per-peer traffic ledger so the parent can assert root
    egress stays O(children(root))."""
    import hashlib
    import time

    from ..data_dist.matrix import VectorTwoDimCyclic

    nbytes = int(_params.get("comm_coll_bench_bytes"))
    mb = max(nbytes // 4, 1)                       # float32 elements
    V = VectorTwoDimCyclic(
        "V", lm=mb * nranks, mb=mb, P=nranks, myrank=rank,
        init_fn=lambda m, size: (
            np.arange(size, dtype=np.float32) * 0.5 + 7.0 if m == 0
            else np.zeros(size, np.float32)))
    t0 = time.perf_counter()
    ctx.add_taskpool(bcast_taskpool(V, n=nranks))
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    bcast_s = time.perf_counter() - t0

    mine = np.asarray(V.data_of(rank).newest_copy().value)
    digest = hashlib.sha256(np.ascontiguousarray(mine).tobytes()).hexdigest()

    # reduction: every rank contributes rank+1 over a small tile
    R = VectorTwoDimCyclic(
        "R", lm=64 * nranks, mb=64, P=nranks, myrank=rank,
        init_fn=lambda m, size: np.full(size, float(m + 1), np.float32))
    O = VectorTwoDimCyclic("O", lm=64, mb=64, P=1, myrank=rank,
                           init_fn=lambda m, size:
                           np.zeros(size, np.float32))
    t0 = time.perf_counter()
    ctx.add_taskpool(reduce_taskpool(R, O, op="sum", n=nranks))
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    reduce_s = time.perf_counter() - t0
    red = float(np.asarray(O.data_of(0).newest_copy().value)[0]) \
        if rank == 0 else None

    fab = ctx.comm_engine.ce.fabric
    stats = fab.peer_stats() if hasattr(fab, "peer_stats") else {}
    return {"rank": rank, "digest": digest, "bcast_s": bcast_s,
            "reduce_s": reduce_s, "reduce0": red, "peer_stats": stats,
            "tree": _params.get("comm_bcast_tree")}


_params.register("comm_coll_bench_bytes", 4 << 20,
                 "payload size of the comm_ranks collective sweep tile "
                 "(also the 8-rank acceptance broadcast)")
