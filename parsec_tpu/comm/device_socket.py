"""Device-resident multi-PROCESS transport: the deployable DCN tier.

The last structural gap VERDICT r3 named between "dryrun-certified" and
"deployable" multi-chip: the plain socket tier kept every payload as a host
object end to end, while the reference's one transport is fully
deployment-grade (one-sided put/get over the real network,
``parsec_mpi_funnelled.c:885-1050``).  This module is the TPU-native analog:

- **Each process binds ONE JAX device** (its local accelerator; the forced
  CPU backend in tests — genuinely separate address spaces either way).
- **Registration is residency**: ``mem_register`` pins the payload on the
  owner's device, exactly like the in-process device tier
  (:mod:`parsec_tpu.comm.device_fabric`).
- **GET payloads move device-to-device with one staging hop per side**:
  serve = D2H of the registered device buffer, wire = binary frames carry
  the flat buffer scatter-gather (no host object graph — dtype/shape ride
  as frame metadata; ≥``comm_get_frag_bytes`` payloads stream as windowed
  fragments that ``recv_into`` the host staging destination), land = H2D
  straight onto the consumer's device.  On DCN
  the two staging hops are physics (NICs read host memory — the reference's
  MPI transport stages identically); on-pod ICI payloads belong to the
  compiled SPMD path (``lower_taskpool(mesh=)``), not this engine.
- **Control AMs stay on the eager CTRL-frame path** (tiny records through
  the structured codec, the reference's eager-protocol split).
- **Bytes are accounted per tier**: ``payload_bytes_out``/``payload_bytes_in``
  (D2H/H2D payload traffic) vs the fabric's total framed bytes — the
  device.h:151-156 traffic-counter role.

Bootstrap: :func:`maybe_init_distributed` initializes ``jax.distributed``
when a coordinator is configured (``PARSEC_TPU_COORDINATOR``,
``PARSEC_TPU_NUM_PROCS``) — the real-pod path where each process then sees
its local chips; without it each process keeps its default local backend.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import numpy as np

from .device_fabric import is_device_array
from .engine import MemHandle
from .socket_fabric import SocketCommEngine, SocketFabric

__all__ = ["DeviceSocketCommEngine", "maybe_init_distributed"]


def maybe_init_distributed() -> bool:
    """Initialize ``jax.distributed`` from the environment if a coordinator
    is configured (the real-pod bootstrap: every process calls this before
    touching jax, then sees its own local chips).  Returns whether the
    distributed runtime was initialized."""
    coord = os.environ.get("PARSEC_TPU_COORDINATOR")
    if not coord:
        return False
    import jax
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["PARSEC_TPU_NUM_PROCS"]),
        process_id=int(os.environ["PARSEC_TPU_PROC_ID"]))
    return True


class DeviceSocketCommEngine(SocketCommEngine):
    """The comm-engine vtable over TCP with device-resident payloads."""

    def __init__(self, fabric: SocketFabric, device: Any = None) -> None:
        super().__init__(fabric)
        if device is None:
            import jax
            device = jax.local_devices()[0]
        self.device = device
        self.payload_bytes_out = 0    # D2H + wire payload bytes served
        self.payload_bytes_in = 0     # wire + H2D payload bytes landed

    # -- registration is residency -------------------------------------------
    def mem_register(self, value: Any, refcount: int = 1,
                     on_drained: Callable[[], None] | None = None,
                     owned: bool = False,
                     peers: set[int] | None = None) -> MemHandle:
        import jax
        if not owned and isinstance(value, np.ndarray) \
                and self.device.platform == "cpu":
            # device_put may zero-copy-alias host memory on the CPU backend
            # only; a real accelerator already pays a physical H2D copy, so
            # the defensive host copy would be pure critical-path waste
            value = value.copy()
        if not is_device_array(value) or value.device != self.device:
            value = jax.device_put(value, self.device)
        return super().mem_register(value, refcount, on_drained, owned=True,
                                    peers=peers)

    # -- the payload wire path: flat buffers + metadata, no object graph -----
    def _serve_value(self, h: MemHandle) -> Any:
        """The D2H staging hop: GETs of a device-registered buffer serve
        the host ndarray, which the binary framing then ships as raw
        scatter-gather segments (single reply) or windowed DATA-frame
        fragments — the pickle VM never sees payload bytes."""
        arr = np.asarray(h.value)
        self.payload_bytes_out += arr.nbytes
        return arr

    def _land_value(self, value: Any) -> Any:
        """The H2D landing hop: fragments recv_into the preallocated host
        destination; completion puts it on MY device."""
        if isinstance(value, np.ndarray):
            import jax
            value = jax.device_put(value, self.device)
            self.payload_bytes_in += value.nbytes
        return value

    def tier_bytes(self) -> dict:
        """Traffic accounting per tier: payload (device path) vs total
        framed bytes on the wire (control = total - payload)."""
        total = getattr(self.fabric, "bytes_sent", 0)
        return {"payload_out": self.payload_bytes_out,
                "payload_in": self.payload_bytes_in,
                "wire_total_sent": total,
                "control_sent": max(0, total - self.payload_bytes_out)}
