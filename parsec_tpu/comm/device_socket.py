"""Device-resident multi-PROCESS transport: the deployable DCN tier.

The last structural gap VERDICT r3 named between "dryrun-certified" and
"deployable" multi-chip: the plain socket tier kept every payload as a host
object end to end, while the reference's one transport is fully
deployment-grade (one-sided put/get over the real network,
``parsec_mpi_funnelled.c:885-1050``).  This module is the TPU-native analog:

- **Each process binds ONE JAX device** (its local accelerator; the forced
  CPU backend in tests — genuinely separate address spaces either way).
- **Registration is residency**: ``mem_register`` pins the payload on the
  owner's device, exactly like the in-process device tier
  (:mod:`parsec_tpu.comm.device_fabric`).
- **GET payloads move device-to-device with one staging hop per side**:
  serve = D2H of the registered device buffer to raw bytes, wire = the TCP
  frame carries the flat buffer (no host object graph — dtype/shape ride
  as metadata), land = H2D straight onto the consumer's device.  On DCN
  the two staging hops are physics (NICs read host memory — the reference's
  MPI transport stages identically); on-pod ICI payloads belong to the
  compiled SPMD path (``lower_taskpool(mesh=)``), not this engine.
- **Control AMs stay on the pickled socket path** (tiny eager records, the
  reference's eager-protocol split).
- **Bytes are accounted per tier**: ``payload_bytes_out``/``payload_bytes_in``
  (D2H/H2D payload traffic) vs the fabric's total framed bytes — the
  device.h:151-156 traffic-counter role.

Bootstrap: :func:`maybe_init_distributed` initializes ``jax.distributed``
when a coordinator is configured (``PARSEC_TPU_COORDINATOR``,
``PARSEC_TPU_NUM_PROCS``) — the real-pod path where each process then sees
its local chips; without it each process keeps its default local backend.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import numpy as np

from .device_fabric import is_device_array
from .engine import AM_TAG_GET_REPLY, MemHandle
from .socket_fabric import SocketCommEngine, SocketFabric

__all__ = ["DeviceSocketCommEngine", "maybe_init_distributed"]


def maybe_init_distributed() -> bool:
    """Initialize ``jax.distributed`` from the environment if a coordinator
    is configured (the real-pod bootstrap: every process calls this before
    touching jax, then sees its own local chips).  Returns whether the
    distributed runtime was initialized."""
    coord = os.environ.get("PARSEC_TPU_COORDINATOR")
    if not coord:
        return False
    import jax
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["PARSEC_TPU_NUM_PROCS"]),
        process_id=int(os.environ["PARSEC_TPU_PROC_ID"]))
    return True


class DeviceSocketCommEngine(SocketCommEngine):
    """The comm-engine vtable over TCP with device-resident payloads."""

    def __init__(self, fabric: SocketFabric, device: Any = None) -> None:
        super().__init__(fabric)
        if device is None:
            import jax
            device = jax.local_devices()[0]
        self.device = device
        self.payload_bytes_out = 0    # D2H + wire payload bytes served
        self.payload_bytes_in = 0     # wire + H2D payload bytes landed

    # -- registration is residency -------------------------------------------
    def mem_register(self, value: Any, refcount: int = 1,
                     on_drained: Callable[[], None] | None = None,
                     owned: bool = False,
                     peers: set[int] | None = None) -> MemHandle:
        import jax
        if not owned and isinstance(value, np.ndarray) \
                and self.device.platform == "cpu":
            # device_put may zero-copy-alias host memory on the CPU backend
            # only; a real accelerator already pays a physical H2D copy, so
            # the defensive host copy would be pure critical-path waste
            value = value.copy()
        if not is_device_array(value) or value.device != self.device:
            value = jax.device_put(value, self.device)
        return super().mem_register(value, refcount, on_drained, owned=True,
                                    peers=peers)

    # -- the payload wire path: flat buffer + metadata, no object graph ------
    def _serve_get(self, eng: Any, src: int, msg: dict) -> None:
        h = self.mem_retrieve(msg["handle"])
        if h is None:
            raise RuntimeError(
                f"rank {self.rank}: GET for unknown handle {msg['handle']}")
        arr = np.asarray(h.value)               # the D2H staging hop
        raw = arr.tobytes()
        self.payload_bytes_out += len(raw)
        self.send_am(AM_TAG_GET_REPLY, msg["reply_to"],
                     {"get_id": msg["get_id"], "raw": raw,
                      "dtype": str(arr.dtype), "shape": arr.shape})
        self.mem_release(msg["handle"], peer=msg["reply_to"])

    def _finish_get(self, eng: Any, src: int, msg: dict) -> None:
        if "raw" in msg:
            import jax
            arr = np.frombuffer(
                msg["raw"], dtype=np.dtype(msg["dtype"])).reshape(
                msg["shape"])
            value = jax.device_put(arr, self.device)  # the H2D landing hop
            self.payload_bytes_in += value.nbytes
            msg = {"get_id": msg["get_id"], "value": value}
        super()._finish_get(eng, src, msg)

    def tier_bytes(self) -> dict:
        """Traffic accounting per tier: payload (device path) vs total
        framed bytes on the wire (control = total - payload)."""
        total = getattr(self.fabric, "bytes_sent", 0)
        return {"payload_out": self.payload_bytes_out,
                "payload_in": self.payload_bytes_in,
                "wire_total_sent": total,
                "control_sent": max(0, total - self.payload_bytes_out)}
