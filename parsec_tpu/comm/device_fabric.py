"""Device-backed comm transport: per-rank device buffers, D2D payload moves.

The TPU-native counterpart of the reference's MPI transport
(``parsec_mpi_funnelled.c:885-1050``) behind the same comm-engine vtable
(``parsec_comm_engine.h:176-199``):

- **Each rank owns one JAX device.**  ``mem_register`` pins the payload onto
  the owner rank's device (the "registered HBM buffer" of SURVEY §5.8) —
  registration IS residency, there is no separate pinning step because XLA
  owns physical HBM.
- **``get`` is a device-to-device transfer**: the consumer runs
  ``jax.device_put(buf, my_device)`` on the owner's device-resident buffer.
  On a real pod this lowers to an ICI DMA between chips (same-host chips:
  direct D2D; cross-host: DCN); on the virtual CPU mesh it is a
  host-buffer copy between the N virtual devices — the same code path the
  driver's dryrun certifies.
- **Active messages stay host-side** (activation AMs are tiny control
  records; the reference keeps them on MPI's eager path for the same
  reason).  They ride the in-process inbox here and a DCN side channel on a
  real deployment.

TPU-first redesign note: JAX arrays are **immutable**, so the reference's
refcounted-snapshot discipline around registered buffers collapses —
``mem_register`` may alias the live buffer (no defensive copy), every
consumer's GET materializes its own device-local copy, and the WAR hazards
the reference guards against (``remote_dep_mpi.c:1546-1604``) cannot occur.
That is the single biggest simplification the XLA data model buys the
transport layer.
"""

from __future__ import annotations

import sys
from typing import Any, Callable

import numpy as np

from ..core.params import params as _params
from .engine import InprocCommEngine, InprocFabric, MemHandle, _LandingZone


def is_device_array(value: Any) -> bool:
    """True for a JAX array (committed or not) without forcing a jax import."""
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(value, jax.Array)


class DeviceFabric(InprocFabric):
    """N ranks, each pinned to one JAX device of the process.

    Control messages share the in-process inbox machinery; payload buffers
    live device-resident on the owner rank's device and move device-to-device
    at GET time.
    """

    def __init__(self, nranks: int, devices: list | None = None) -> None:
        super().__init__(nranks)
        if devices is None:
            import jax
            devices = list(jax.devices())
        if len(devices) < nranks:
            raise ValueError(
                f"device fabric needs {nranks} devices, found {len(devices)}")
        self.devices = devices[:nranks]

    def attach(self, rank: int) -> "DeviceCommEngine":
        eng = DeviceCommEngine(self, rank)
        self.engines[rank] = eng
        return eng


class DeviceCommEngine(InprocCommEngine):
    """The comm-engine vtable over per-rank JAX devices."""

    def __init__(self, fabric: DeviceFabric, rank: int) -> None:
        super().__init__(fabric, rank)
        self.device = fabric.devices[rank]
        self.bytes_put = 0   # D2D traffic accounting (device.h:151-156 analog)
        self.bytes_got = 0

    def mem_register(self, value: Any, refcount: int = 1,
                     on_drained: Callable[[], None] | None = None,
                     owned: bool = False,
                     peers: set[int] | None = None) -> MemHandle:
        """Pin ``value`` on this rank's device and publish it.

        numpy payloads are snapshotted (``device_put`` on the CPU backend
        zero-copy-aliases aligned host buffers, so an explicit copy is
        required before the upload); device arrays are aliased directly
        (immutable — see module docstring), so registration of an
        already-resident tile is free.
        """
        import jax
        if not owned and isinstance(value, np.ndarray):
            value = value.copy()
        if not is_device_array(value) or value.device != self.device:
            value = jax.device_put(value, self.device)
        self.bytes_put += getattr(value, "nbytes", 0)
        # the copy/upload above is the snapshot: ownership is settled
        return super().mem_register(value, refcount, on_drained, owned=True,
                                    peers=peers)

    def _land_value(self, value: Any) -> Any:
        """Land the payload on MY device (the ICI D2D pull)."""
        import jax
        if is_device_array(value):
            value = jax.device_put(value, self.device)
            self.bytes_got += value.nbytes
        return value

    # -- windowed multi-buffer pipelining of large D2D pulls ------------------
    def _plan_frags(self, value: Any) -> tuple[list, dict] | None:
        """Device arrays above the fragment threshold move as a window of
        device sub-buffers: the owner slices device-side (no host staging),
        each fragment is its own ``device_put`` on arrival — overlapped
        with task execution by the receiver's progress interleaving — and
        completion reassembles on the consumer's device."""
        if not is_device_array(value):
            return super()._plan_frags(value)
        fb = _params.get("comm_get_frag_bytes")
        if not fb or value.nbytes <= fb:
            return None
        itemsize = np.dtype(value.dtype).itemsize
        per = max(int(fb) // itemsize, 1)
        flat = value.reshape(-1)
        pieces = []
        for e0 in range(0, flat.shape[0], per):
            piece = flat[e0:e0 + per]
            pieces.append((e0 * itemsize, piece.nbytes, piece))
        meta = {"shape": tuple(value.shape), "dtype": np.dtype(value.dtype).str,
                "nbytes": value.nbytes, "nfrags": len(pieces),
                "tier": "device"}
        return pieces, meta

    def _zone_write(self, zone: _LandingZone, offset: int, data: Any) -> None:
        if zone.frags is None:
            super()._zone_write(zone, offset, data)
            return
        import jax
        zone.frags[offset] = jax.device_put(data, self.device)

    def _zone_finish(self, zone: _LandingZone) -> Any:
        if zone.frags is None:
            return super()._zone_finish(zone)
        import jax.numpy as jnp
        parts = [zone.frags[off] for off in sorted(zone.frags)]
        # bytes_got is counted by _land_value (a same-device put is free)
        return jnp.concatenate(parts).reshape(zone.meta["shape"])
