"""Fourcounter: distributed termination detection by counting waves.

Rebuild of ``parsec/mca/termdet/fourcounter`` (SURVEY §2.4): local counters
alone cannot terminate a distributed taskpool — a rank with zero remaining
local tasks may still have a message in flight toward it.  The fourcounter
scheme (Mattern's four-counter / double-wave method) circulates a control
token around the rank ring accumulating

- ``S`` — total dependency-activation messages *sent* by all ranks,
- ``R`` — total activation messages *received* (counted at delivery),
- ``idle`` — every rank locally idle (nb_tasks == nb_pending_actions == 0).

Rank 0 concludes termination when a wave returns fully idle with ``S == R``
**and** the pair matches the previous wave (no traffic moved between two
consecutive global snapshots); it then sends a TERMINATE token around the
ring and every rank fires its taskpool's termination callback.  A rank that
is busy when the token arrives holds it until it goes idle
(``termdet_fourcounter_module.c``'s deferred wave participation).

The token rides the reserved :data:`~parsec_tpu.comm.engine.AM_TAG_TERMDET`
tag (cf. the reference reserving a comm-engine tag for its waves,
``parsec_comm_engine.h:35``).  Rendezvous-GET acknowledgements need no
counting: the sender holds a pending action until the consumer acks, so
unfinished transfers keep their sender busy and block the wave.
"""

from __future__ import annotations

from typing import Any

from ..core.mca import Component, component
from ..runtime.termdet import (STATE_BUSY, STATE_IDLE, STATE_TERMINATED,
                               TermDetMonitor)


class FourCounterTermDet(TermDetMonitor):
    """Per-taskpool monitor; one instance per rank, linked over the ring."""

    name = "fourcounter"

    def __init__(self, context: Any) -> None:
        super().__init__()
        self.ctx = context
        self.msgs_sent = 0
        self.msgs_recv = 0
        self._held_tokens: list[dict] = []
        self._kick_wave = False
        # rank 0 only: previous wave snapshot + single-outstanding-wave flag
        # (overlapping waves would break the consecutive-snapshot premise)
        self._prev_wave: tuple[int, int] | None = None
        self._wave_out = False

    # -- engine plumbing ------------------------------------------------------
    @property
    def _engine(self):
        return self.ctx.comm_engine

    def _comm_id(self) -> int:
        return self.taskpool.comm_id

    def on_comm_sent(self) -> None:
        with self._lock:
            self.msgs_sent += 1

    def on_comm_recv(self) -> None:
        with self._lock:
            self.msgs_recv += 1

    # -- state machine --------------------------------------------------------
    # the base-class mutators call _check_idle_locked and _terminate on True;
    # here going idle never terminates directly — it releases a wave instead
    def _check_idle_locked(self) -> bool:
        if self.ctx is None or self.ctx.nb_ranks <= 1:
            return super()._check_idle_locked()
        if (self.state == STATE_BUSY and self.nb_tasks == 0
                and self.nb_pending_actions == 0):
            self.state = STATE_IDLE
            self._kick_wave = True
        elif self.state == STATE_IDLE and (self.nb_tasks > 0
                                           or self.nb_pending_actions > 0):
            self.state = STATE_BUSY
        return False

    # hook into the mutators' unlock point: the base class only calls
    # _terminate() when _check_idle_locked returned True, so we piggyback on
    # the public mutators to flush wave work after the lock drops
    def taskpool_addto_nb_tasks(self, delta: int) -> int:
        n = super().taskpool_addto_nb_tasks(delta)
        self._flush_wave_work()
        return n

    def taskpool_addto_nb_pa(self, delta: int) -> int:
        n = super().taskpool_addto_nb_pa(delta)
        self._flush_wave_work()
        return n

    def ready(self) -> None:
        super().ready()
        self._flush_wave_work()

    def _flush_wave_work(self) -> None:
        if self.ctx is None or self.ctx.nb_ranks <= 1:
            return
        if not self._kick_wave:  # unlocked fast path: flag set under the
            return               # same lock by the mutator that just ran
        tokens: list[dict] = []
        start = False
        with self._lock:
            if self.state != STATE_IDLE or not self._kick_wave:
                return
            self._kick_wave = False
            if self._held_tokens:
                tokens, self._held_tokens = self._held_tokens, []
            elif self.ctx.my_rank == 0 and not self._wave_out:
                self._wave_out = True
                start = True
        for token in tokens:
            self._contribute_and_forward(token)
        if start:
            self._start_wave()

    # -- waves ----------------------------------------------------------------
    def _start_wave(self) -> None:
        self._contribute_and_forward({
            "tp": self._comm_id(), "kind": "wave", "S": 0, "R": 0,
            "idle": True,
        })

    def _contribute_and_forward(self, token: dict) -> None:
        with self._lock:
            token["S"] += self.msgs_sent
            token["R"] += self.msgs_recv
            token["idle"] = token["idle"] and self.state == STATE_IDLE
        nxt = (self.ctx.my_rank + 1) % self.ctx.nb_ranks
        self._engine.send_termdet(nxt, token)

    def on_token(self, token: dict) -> None:
        """A wave or terminate token arrived for this taskpool."""
        if token["kind"] == "term":
            self._ring_terminate(forward=True)
            return
        if self.ctx.my_rank == 0:
            self._conclude_wave(token)
            return
        with self._lock:
            if self.state != STATE_IDLE:
                # busy: hold the token until the local counters drain
                self._held_tokens.append(token)
                return
        self._contribute_and_forward(token)

    def _conclude_wave(self, token: dict) -> None:
        with self._lock:
            self._wave_out = False
            my_idle = self.state == STATE_IDLE
        snap = (token["S"], token["R"])
        if (token["idle"] and my_idle and token["S"] == token["R"]
                and self._prev_wave == snap):
            self._ring_terminate(forward=True)
            return
        self._prev_wave = snap
        with self._lock:
            if my_idle and not self._wave_out:
                self._wave_out = True
            else:
                # re-kick when we next go idle
                self._kick_wave = True
                return
        self._start_wave()

    def _ring_terminate(self, forward: bool) -> None:
        nxt = (self.ctx.my_rank + 1) % self.ctx.nb_ranks
        if forward and nxt != 0:
            self._engine.send_termdet(
                nxt, {"tp": self._comm_id(), "kind": "term"})
        fire = False
        with self._lock:
            if self.state != STATE_TERMINATED:
                self.state = STATE_TERMINATED
                fire = True
        if fire:
            self._terminate()


@component
class FourCounterComponent(Component):
    type_name = "termdet"
    name = "fourcounter"
    priority = 10

    def query(self, context: Any = None) -> bool:
        return False  # only by explicit request (--mca termdet fourcounter)

    def open(self, context: Any = None) -> FourCounterTermDet:
        return FourCounterTermDet(context)
