"""The comm-engine abstraction and the in-process fabric backend.

Rebuild of ``parsec_comm_engine.h`` (SURVEY §2.6): a transport exposes

- **active messages** — ``tag_register(tag, cb)`` + ``send_am(tag, dst,
  payload)``: small fixed-role control messages delivered by invoking the
  registered callback on the receiver during its ``progress()``
  (``parsec_comm_engine.h:60-93``);
- **registered memory + one-sided get** — ``mem_register`` publishes a local
  buffer under a :class:`MemHandle`; a peer pulls it with :meth:`get`
  (rendezvous protocol, ``parsec_comm_engine.h:95-113``), completion invoking
  a local callback and an optional remote-completion AM;
- **progress** — drains incoming traffic; never called concurrently for one
  engine (the funnelled discipline of ``parsec_mpi_funnelled.c``).

Reserved AM tags mirror ``parsec_comm_engine.h:24-40``.

Backends:

- :class:`InprocCommEngine` over :class:`InprocFabric` — N ranks inside one
  process with per-rank message queues.  This is the rebuild's analog of the
  reference's oversubscribed-MPI test runs (SURVEY §4): the *protocol* layer
  (remote_dep) is exercised unchanged; only the byte transport is local.
  ``get`` copies the source buffer (the stand-in for an ICI DMA read).
- :class:`~parsec_tpu.comm.device_fabric.DeviceCommEngine` over
  :class:`~parsec_tpu.comm.device_fabric.DeviceFabric` — the device-backed
  transport: each rank owns one JAX device, ``mem_register`` pins payloads
  device-resident, ``get`` is a device-to-device ``jax.device_put`` (ICI DMA
  on hardware), AMs stay host-side; see §5.8 of SURVEY.md for the mapping.
- :class:`~parsec_tpu.comm.socket_fabric.SocketCommEngine` over
  :class:`~parsec_tpu.comm.socket_fabric.SocketFabric` — ranks as separate
  OS processes over TCP (the DCN tier; launched by
  :func:`parsec_tpu.comm.multiproc.run_multiproc`, the mpiexec analog).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np

from ..core.params import params as _params
from ..prof import pins, spans as _spans
from ..prof.pins import PinsEvent

_now_ns = time.perf_counter_ns

# Reserved AM tags (cf. parsec_comm_engine.h:24-40).
AM_TAG_GET_REQ = 1       # internal: rendezvous pull request
AM_TAG_GET_REPLY = 2     # internal: rendezvous payload delivery
AM_TAG_GET_ACK = 3       # remote-completion notification after a get
AM_TAG_ACTIVATE = 4      # remote-dep activation
AM_TAG_TERMDET = 5       # termination-detection waves (fourcounter)
AM_TAG_BARRIER = 6       # context-level sync barrier
AM_TAG_DTD = 7           # DTD cross-rank data pushes / flushes
AM_TAG_GET_FRAG = 8      # internal: one rendezvous payload fragment
AM_TAG_GET_FRAG_ACK = 9  # internal: fragment credit (windowed pipelining)
AM_TAG_USER_BASE = 16    # first tag available to applications/DSLs

_params.register("comm_get_frag_bytes", 4 << 20,
                 "rendezvous GETs above this many bytes are split into "
                 "fragments of this size and pipelined (0 = monolithic "
                 "replies, the pre-fragmentation wire path)")
_params.register("comm_get_window", 4,
                 "max in-flight unacked fragments per GET (the sender-side "
                 "window; each landed fragment returns one credit)")
# the autotuner's declared domains (docs/TUNING.md): fragment sizes move
# in powers of two between 256KiB and 16MiB, the window between 1 and 16
_params.declare_knob("comm_get_frag_bytes", lo=256 << 10, hi=16 << 20,
                     scale="log2")
_params.declare_knob("comm_get_window", lo=1, hi=16, scale="log2")


class Capabilities:
    """What a backend supports (cf. ``parsec_comm_engine_capabilities_t``)."""

    __slots__ = ("sided", "multithreaded", "supports_noncontiguous")

    def __init__(self, sided: int = 1, multithreaded: bool = True,
                 supports_noncontiguous: bool = True) -> None:
        self.sided = sided
        self.multithreaded = multithreaded
        self.supports_noncontiguous = supports_noncontiguous


class MemHandle:
    """A published local buffer (cf. ``mem_register`` handles).

    ``refcount`` counts peers still expected to pull; the publisher drops the
    registration when it reaches zero (the ``mem_unregister`` moment).
    ``peers`` optionally names the consumer ranks — a peer that dies before
    its GET then releases its reference through
    :meth:`CommEngine.on_peer_failed` instead of pinning the buffer forever.
    """

    __slots__ = ("handle_id", "rank", "value", "refcount", "on_drained",
                 "peers")

    _ids = itertools.count(1)

    def __init__(self, rank: int, value: Any, refcount: int = 1,
                 on_drained: Callable[[], None] | None = None,
                 peers: set[int] | None = None) -> None:
        self.handle_id = next(MemHandle._ids)
        self.rank = rank
        self.value = value
        self.refcount = refcount
        self.on_drained = on_drained
        self.peers = set(peers) if peers is not None else None

    def wire(self) -> tuple[int, int]:
        """The on-the-wire form: (owner rank, handle id)."""
        return (self.rank, self.handle_id)


class _FragSend:
    """Sender-side state of one fragmented rendezvous reply: the ordered
    piece list plus the send cursor the credit window advances."""

    __slots__ = ("dst", "get_id", "handle_id", "pieces", "meta", "next",
                 "trace", "t0")

    def __init__(self, dst: int, get_id: int, handle_id: int,
                 pieces: list, meta: dict, trace: int = 0,
                 t0: int = 0) -> None:
        self.dst = dst
        self.get_id = get_id
        self.handle_id = handle_id
        self.pieces = pieces        # [(byte_offset, nbytes, buffer), ...]
        self.meta = meta
        self.next = 0
        self.trace = trace          # 8-byte trace context (prof/spans.py)
        self.t0 = t0                # serve-span open timestamp (ns)


class _LandingZone:
    """Receiver-side state of one fragmented GET: the preallocated final
    destination fragments ``recv_into`` (host tier) or accumulate onto
    (device tier), plus landed-offset dedup for transport replays."""

    __slots__ = ("get_id", "src", "meta", "dest", "flat", "remaining",
                 "landed", "frags")

    def __init__(self, get_id: int, src: int, meta: dict) -> None:
        self.get_id = get_id
        self.src = src
        self.meta = meta
        self.dest = None            # host tier: the final ndarray
        self.flat = None            # its flat uint8 view (recv_into target)
        self.remaining = int(meta["nbytes"])
        self.landed: set[int] = set()
        self.frags: dict[int, Any] | None = None   # device tier pieces


class InprocFabric:
    """Process-global N-rank fabric: per-rank inboxes + engine registry."""

    def __init__(self, nranks: int) -> None:
        self.nranks = nranks
        self._inboxes: list[deque] = [deque() for _ in range(nranks)]
        self._locks = [threading.Lock() for _ in range(nranks)]
        self.engines: list["InprocCommEngine | None"] = [None] * nranks

    def attach(self, rank: int) -> "InprocCommEngine":
        eng = InprocCommEngine(self, rank)
        self.engines[rank] = eng
        return eng

    def deliver(self, dst: int, tag: int, src: int, payload: Any,
                trace_id: int = 0) -> None:
        # trace_id is a wire-header concern (socket_fabric packs it into
        # the CTRL header's u2 word); the in-process fabric has no frame
        # headers, and the payload-level trace fields already carry it
        with self._locks[dst]:
            self._inboxes[dst].append((tag, src, payload))

    def drain(self, rank: int, limit: int = 64) -> list[tuple]:
        out = []
        with self._locks[rank]:
            while self._inboxes[rank] and len(out) < limit:
                out.append(self._inboxes[rank].popleft())
        return out

    def pending(self, rank: int) -> int:
        with self._locks[rank]:
            return len(self._inboxes[rank])


class CommEngine:
    """The abstract vtable (``parsec_comm_engine.h:176-199``)."""

    capabilities = Capabilities()

    def __init__(self, nranks: int, rank: int) -> None:
        self.nranks = nranks
        self.rank = rank
        self._am_callbacks: dict[int, Callable] = {}
        self._mem: dict[int, MemHandle] = {}
        self._mem_lock = threading.Lock()
        self._enabled = False
        self.prefetch_gets = 0     # lookahead GETs issued (prefetch_get)
        # upper-layer flush callback (the remote-dep outgoing stage): every
        # progress() drives it, so loops that spin on raw engine progress
        # (sync, quiesce) can never strand staged sends
        self.flush_hook: Callable[[], int] | None = None

    # -- active messages ----------------------------------------------------
    def tag_register(self, tag: int, cb: Callable[[Any, int, Any], None]) -> None:
        """``cb(engine, src_rank, payload)`` runs during ``progress``."""
        self._am_callbacks[tag] = cb

    def send_am(self, tag: int, dst: int, payload: Any,
                trace_id: int = 0) -> None:
        """``trace_id`` (optional 8-byte trace context, prof/spans.py)
        rides the frame header on binary-framed transports — payload
        semantics are untouched."""
        raise NotImplementedError

    # -- registered memory / one-sided ---------------------------------------
    def mem_register(self, value: Any, refcount: int = 1,
                     on_drained: Callable[[], None] | None = None,
                     owned: bool = False,
                     peers: set[int] | None = None) -> MemHandle:
        """Publish a buffer for one-sided GETs.

        The engine needs a stable snapshot (the last consumer may receive the
        registered buffer itself, not a copy), so mutable host arrays are
        copied here unless the caller asserts ownership with ``owned=True``
        — the invariant lives at the API boundary, not in caller convention.
        Immutable payloads (JAX arrays) alias safely either way.

        ``peers`` names the consumer ranks expected to pull (one reference
        each); :meth:`on_peer_failed` then releases a dead peer's share.
        """
        if not owned and isinstance(value, np.ndarray):
            value = value.copy()
        h = MemHandle(self.rank, value, refcount, on_drained, peers=peers)
        with self._mem_lock:
            self._mem[h.handle_id] = h
        return h

    def mem_retrieve(self, handle_id: int) -> MemHandle | None:
        with self._mem_lock:
            return self._mem.get(handle_id)

    def mem_release(self, handle_id: int, peer: int | None = None) -> None:
        """Drop one reference; unregister when drained."""
        with self._mem_lock:
            h = self._mem.get(handle_id)
            if h is None:
                return
            h.refcount -= 1
            if peer is not None and h.peers is not None:
                h.peers.discard(peer)
            if h.refcount > 0:
                return
            del self._mem[handle_id]
        if h.on_drained is not None:
            h.on_drained()

    def on_peer_failed(self, rank: int) -> int:
        """Release every registration share held for a dead peer — the
        buffer-GC moment the reference performs at communicator teardown
        (``parsec_mpi_funnelled.c:431``), here per-peer so a failed rank
        cannot pin its producers' memory forever.  Returns the number of
        handles fully drained by this."""
        drained = []
        with self._mem_lock:
            for hid in list(self._mem):
                h = self._mem[hid]
                if h.peers is None or rank not in h.peers:
                    continue
                h.peers.discard(rank)
                h.refcount -= 1
                if h.refcount <= 0:
                    del self._mem[hid]
                    drained.append(h)
        for h in drained:
            if h.on_drained is not None:
                h.on_drained()
        return len(drained)

    def get(self, rwire: tuple[int, int],
            on_complete: Callable[[Any], None],
            trace: int | None = None) -> None:
        """One-sided pull of the remote buffer named by ``rwire``;
        ``on_complete(value)`` runs locally when the payload has landed.
        ``trace`` is an optional 8-byte trace id (prof/spans.py): it
        rides the GET request so BOTH ends span-record the transfer
        under the originating request's trace."""
        raise NotImplementedError

    def prefetch_get(self, rwire: tuple[int, int],
                     on_complete: Callable[[Any], None],
                     trace: int | None = None) -> None:
        """A GET issued AHEAD of demand (ISSUE 11): same wire protocol
        — credit-windowed fragmented replies included — but tallied
        separately (``prefetch_gets``, the COMM_GET_PREFETCH PINS
        event → ``runtime_report``'s comm block, and ``frag_state`` in
        stall dumps) so wavefront lookahead (the KV tier map paging a
        cold sequence back one superpool early) is distinguishable
        from on-demand dependency pulls."""
        self.prefetch_gets += 1
        pins.fire(PinsEvent.COMM_GET_PREFETCH, None, rwire[0])
        self.get(rwire, on_complete, trace=trace)

    # -- lifecycle / progress -------------------------------------------------
    def enable(self) -> None:
        self._enabled = True

    def progress(self) -> int:
        """Drain incoming traffic; returns number of events handled."""
        raise NotImplementedError

    def pending(self) -> int:
        """Number of undelivered incoming events (0 if unknowable)."""
        return 0

    def sync(self) -> None:
        """Barrier across ranks (collective; used at context teardown)."""
        raise NotImplementedError

    def fini(self) -> None:
        """Teardown: force-drop every live registration (the reference frees
        registered buffers when the communicator dies)."""
        with self._mem_lock:
            leftovers, self._mem = list(self._mem.values()), {}
        for h in leftovers:
            if h.on_drained is not None:
                h.on_drained()


class InprocCommEngine(CommEngine):
    """N ranks in one process (the oversubscribed-MPI analog, SURVEY §4)."""

    def __init__(self, fabric: InprocFabric, rank: int) -> None:
        super().__init__(fabric.nranks, rank)
        self.fabric = fabric
        self._pending_gets: dict[int, Callable] = {}
        self._get_ids = itertools.count(1)
        self.dup_get_replies = 0
        self._barrier_seen: dict[int, set] = {}
        self._barrier_gen = 0
        self._progress_lock = threading.Lock()
        # fragmented-rendezvous state: receiver landing zones by get_id,
        # sender piece cursors by (dst, get_id).  _frag_active is the
        # lock-free busy-worker gate (a plain int read): nonzero while any
        # zone or send window is open, so workers with plenty of tasks
        # still interleave fragment progress (the T3-style overlap)
        self._landing: dict[int, _LandingZone] = {}
        self._frag_sends: dict[tuple[int, int], _FragSend] = {}
        self._frag_lock = threading.Lock()
        self._frag_active = 0
        # requester-side span state by get_id: (trace_id, t0_ns) —
        # entries exist only while the span recorder is installed, so
        # the disabled path never touches the dict
        self._get_spans: dict[int, tuple[int, int]] = {}
        self.frags_in = 0
        self.frag_bytes_in = 0
        self.frags_out = 0
        self.frag_bytes_out = 0
        self.dup_frags = 0
        self.tag_register(AM_TAG_GET_REQ, self._serve_get)
        self.tag_register(AM_TAG_GET_REPLY, self._finish_get)
        self.tag_register(AM_TAG_GET_FRAG, self._on_frag)
        self.tag_register(AM_TAG_GET_FRAG_ACK, self._on_frag_ack)
        self.tag_register(AM_TAG_BARRIER, self._on_barrier)

    # -- AM -------------------------------------------------------------------
    def send_am(self, tag: int, dst: int, payload: Any,
                trace_id: int = 0) -> None:
        # self-sends also go through the inbox so the callback runs from
        # progress(), never from the sender's stack
        self.fabric.deliver(dst, tag, self.rank, payload,
                            trace_id=trace_id)

    # -- one-sided get: rendezvous through internal AMs ----------------------
    # (the same emulation the reference's MPI backend uses: GET req AM →
    #  source replies with the payload, parsec_mpi_funnelled.c:247,980)
    def get(self, rwire: tuple[int, int],
            on_complete: Callable[[Any], None],
            trace: int | None = None) -> int:
        owner, handle_id = rwire
        get_id = next(self._get_ids)
        self._pending_gets[get_id] = on_complete
        msg = {"handle": handle_id, "get_id": get_id,
               "reply_to": self.rank}
        if _spans.recorder is not None:
            self._get_spans[get_id] = (trace or 0, _now_ns())
        if trace:
            msg["trace"] = trace
        self.send_am(AM_TAG_GET_REQ, owner, msg, trace_id=trace or 0)
        return get_id

    def resume_get(self, rwire: tuple[int, int], get_id: int,
                   trace: int | None = None) -> bool:
        """Re-issue a still-pending GET against a (possibly different)
        owner — the mid-tree fault path: a staging parent died with the
        transfer partially landed, so the requester pulls the REMAINDER
        from a surviving holder (typically the grandparent).  Offsets
        already in the landing zone ride a ``resume`` list on the GET
        request; the new server skips them, and any zombie fragments the
        dead parent managed to emit dedup against ``zone.landed`` exactly
        once.  Returns False when the get already completed (nothing to
        resume)."""
        owner, handle_id = rwire
        if get_id not in self._pending_gets:
            return False
        with self._frag_lock:
            zone = self._landing.get(get_id)
            resume = sorted(zone.landed) if zone is not None else []
            if zone is not None:
                # retarget the zone BEFORE any on_peer_failed(dead parent)
                # sweep: a zone pointing at the dead src would be reaped
                zone.src = owner
        msg = {"handle": handle_id, "get_id": get_id,
               "reply_to": self.rank}
        if resume:
            msg["resume"] = resume
        if trace:
            msg["trace"] = trace
        self.send_am(AM_TAG_GET_REQ, owner, msg, trace_id=trace or 0)
        return True

    def _record_get_span(self, get_id: int, nbytes: int) -> None:
        """Requester-side "comm.get" span: request sent -> payload
        landed, flow-keyed ``get:<requester>:<get_id>`` so tracemerge
        stitches it against the producer's serve span."""
        ent = self._get_spans.pop(get_id, None)
        r = _spans.recorder
        if ent is None or r is None:
            return
        tr, t0 = ent
        r.record("comm.get", tr, t0, _now_ns(),
                 args={"flow": f"get:{self.rank}:{get_id}",
                       "flow_side": "recv", "bytes": nbytes})

    def _serve_get(self, eng: CommEngine, src: int, msg: dict) -> None:
        h = self.mem_retrieve(msg["handle"])
        if h is None:
            raise RuntimeError(
                f"rank {self.rank}: GET for unknown handle {msg['handle']}")
        t0 = _now_ns() if _spans.recorder is not None else 0
        value = self._serve_value(h)
        plan = self._plan_frags(value)
        trace = msg.get("trace") or 0
        landed = set(msg.get("resume") or ())
        if plan is not None and landed:
            # resumed pull: serve only the offsets the requester is still
            # missing (its landing zone keeps what the dead parent shipped)
            pieces, meta = plan
            pieces = [p for p in pieces if p[0] not in landed]
            if not pieces:
                # everything already landed on the requester's side; its
                # zone completes off in-flight fragments — just drop the
                # share this pull would have consumed
                self.mem_release(msg["handle"], peer=msg["reply_to"])
                return
            plan = (pieces, meta)
        if plan is not None:
            # large payload: windowed fragmented reply — the receiver
            # copies fragments into its own preallocated destination, so
            # no sender-side ownership copy is needed here
            self._start_frag_send(msg["reply_to"], msg["get_id"],
                                  msg["handle"], plan, trace=trace, t0=t0)
            return
        # the DMA copy: the receiver must own its bytes (ICI read analog).
        # The registered buffer is already a private snapshot, so the LAST
        # consumer takes ownership of it instead of copying again.
        if isinstance(value, np.ndarray) and h.refcount > 1:
            value = value.copy()
        self.send_am(AM_TAG_GET_REPLY, msg["reply_to"],
                     {"get_id": msg["get_id"], "value": value},
                     trace_id=trace)
        r = _spans.recorder
        if r is not None:
            r.record("comm.get_serve", trace, t0, _now_ns(),
                     args={"flow": f"get:{msg['reply_to']}:"
                                   f"{msg['get_id']}",
                           "flow_side": "emit",
                           "bytes": int(getattr(value, "nbytes", 0))})
        # the puller's share is consumed: clear it from the expected-peer
        # set too, so a LATER death of that rank cannot double-release
        self.mem_release(msg["handle"], peer=msg["reply_to"])

    def _finish_get(self, eng: CommEngine, src: int, msg: dict) -> None:
        with self._frag_lock:
            # a resumed GET answered monolithically (the new owner's frag
            # params differ) supersedes any half-landed zone: retire it or
            # _frag_active would stay pinned forever
            if self._landing.pop(msg["get_id"], None) is not None:
                self._frag_active -= 1
        cb = self._pending_gets.pop(msg["get_id"], None)
        if cb is None:
            # duplicate reply (e.g. a transport-level replay after a
            # reconnect): the first landing completed the get — idempotent
            self.dup_get_replies += 1
            return
        value = self._land_value(msg["value"])
        self._record_get_span(msg["get_id"],
                              int(getattr(value, "nbytes", 0)))
        cb(value)

    # -- fragmentation hooks (overridden by the device tiers) -----------------
    def _serve_value(self, h: MemHandle) -> Any:
        """What a GET of handle ``h`` serves (device tiers stage here)."""
        return h.value

    def _land_value(self, value: Any) -> Any:
        """Final landing transform applied to every completed GET
        (device tiers ``device_put`` here)."""
        return value

    def _plan_frags(self, value: Any) -> tuple[list, dict] | None:
        """Fragmentation plan for a large payload: ``(pieces, meta)`` with
        ``pieces = [(byte_offset, nbytes, buffer), ...]``, or None for the
        monolithic reply path."""
        fb = _params.get("comm_get_frag_bytes")
        if not fb or not isinstance(value, np.ndarray) \
                or value.dtype == object or value.nbytes <= fb:
            return None
        v = value if value.flags.c_contiguous else np.ascontiguousarray(value)
        flat = v.reshape(-1).view(np.uint8)
        pieces = [(off, min(fb, v.nbytes - off), flat[off:off + fb])
                  for off in range(0, v.nbytes, fb)]
        meta = {"shape": tuple(v.shape), "dtype": v.dtype.str,
                "nbytes": v.nbytes, "nfrags": len(pieces), "tier": "host"}
        return pieces, meta

    def _transport_frag(self, dst: int, get_id: int, offset: int,
                        nbytes: int, data: Any, meta: dict | None,
                        last: bool) -> None:
        """Ship one fragment.  In-process: the inbox carries a VIEW of the
        registered buffer; the receiver-side zone copy is the DMA analog.
        The socket tier overrides this with a binary DATA frame whose raw
        bytes ``recv_into`` the destination directly."""
        self.fabric.deliver(dst, AM_TAG_GET_FRAG, self.rank,
                            (get_id, offset, nbytes, meta, data))

    # -- fragmentation: sender side -------------------------------------------
    def _start_frag_send(self, dst: int, get_id: int, handle_id: int,
                         plan: tuple[list, dict], trace: int = 0,
                         t0: int = 0) -> None:
        pieces, meta = plan
        if trace:
            # the first DATA frame's codec meta carries the trace: later
            # fragments resolve through their get_id (docs/OBSERVABILITY)
            meta = dict(meta, trace=trace)
        fs = _FragSend(dst, get_id, handle_id, pieces, meta, trace, t0)
        with self._frag_lock:
            self._frag_sends[(dst, get_id)] = fs
            self._frag_active += 1
        for _ in range(max(int(_params.get("comm_get_window")), 1)):
            if not self._send_next_frag(fs):
                break

    def _send_next_frag(self, fs: _FragSend) -> bool:
        i = fs.next
        if i >= len(fs.pieces):
            return False
        fs.next = i + 1
        off, n, data = fs.pieces[i]
        last = fs.next == len(fs.pieces)
        self._transport_frag(fs.dst, fs.get_id, off, n, data,
                             fs.meta if i == 0 else None, last)
        self.frags_out += 1
        self.frag_bytes_out += n
        pins.fire(PinsEvent.COMM_GET_FRAG_SENT, None, n)
        if last:
            with self._frag_lock:
                self._frag_sends.pop((fs.dst, fs.get_id), None)
                self._frag_active -= 1
            r = _spans.recorder
            if r is not None:
                r.record("comm.get_serve", fs.trace, fs.t0 or _now_ns(),
                         _now_ns(),
                         args={"flow": f"get:{fs.dst}:{fs.get_id}",
                               "flow_side": "emit",
                               "bytes": int(fs.meta.get("nbytes", 0)),
                               "frags": len(fs.pieces)})
            self.mem_release(fs.handle_id, peer=fs.dst)
        return True

    def _on_frag_ack(self, eng: CommEngine, src: int, payload: Any) -> None:
        with self._frag_lock:
            fs = self._frag_sends.get((src, payload[0]))
        if fs is not None:
            self._send_next_frag(fs)

    # -- fragmentation: receiver side -----------------------------------------
    def _zone_alloc(self, get_id: int, src: int, meta: dict) -> _LandingZone:
        zone = _LandingZone(get_id, src, meta)
        if meta.get("tier") == "device":
            zone.frags = {}
        else:
            zone.dest = np.empty(meta["shape"], np.dtype(meta["dtype"]))
            zone.flat = zone.dest.reshape(-1).view(np.uint8)
        return zone

    def landing_view(self, get_id: int, src: int, offset: int, nbytes: int,
                     meta: dict | None) -> memoryview | None:
        """Writable destination slice for a DATA frame's raw bytes — called
        by the socket receive thread so payloads land socket → final buffer
        with no staging hop.  None = duplicate/stale fragment (the caller
        drains the bytes to scratch).

        The offset is NOT marked landed here — only :meth:`landing_commit`
        (after the bytes fully arrived) does that.  A receive that dies
        mid-body therefore leaves no mark, and a concurrent replay on a
        fresh connection may be handed the same slice: both writers carry
        identical bytes, the writes are idempotent, and exactly one commit
        wins."""
        with self._frag_lock:
            zone = self._landing.get(get_id)
            if zone is None:
                if meta is None:
                    return None          # fragment of a completed/stale GET
                zone = self._zone_alloc(get_id, src, meta)
                self._landing[get_id] = zone
                self._frag_active += 1
            if offset in zone.landed:
                return None              # transport replay: already landed
        return memoryview(zone.flat[offset:offset + nbytes]).cast("B")

    def landing_commit(self, get_id: int, offset: int) -> bool:
        """Mark a fully received fragment landed; False = another delivery
        (a replay racing on a second connection) already committed it, or
        the zone is gone — the caller must not double-account it."""
        with self._frag_lock:
            zone = self._landing.get(get_id)
            if zone is None or offset in zone.landed:
                return False
            zone.landed.add(offset)
            return True

    def _zone_write(self, zone: _LandingZone, offset: int, data: Any) -> None:
        n = getattr(data, "nbytes", len(data))
        zone.flat[offset:offset + n] = \
            data if isinstance(data, np.ndarray) \
            else np.frombuffer(data, np.uint8)

    def _zone_finish(self, zone: _LandingZone) -> Any:
        return zone.dest

    def _on_frag(self, eng: CommEngine, src: int, payload: tuple) -> None:
        get_id, offset, nbytes, meta, data = payload
        with self._frag_lock:
            zone = self._landing.get(get_id)
            if zone is None:
                if data is None or meta is None:
                    # socket tier: zone was created by the recv thread and
                    # already retired, or an in-process stale duplicate
                    self.dup_frags += 1
                    return
                zone = self._zone_alloc(get_id, src, meta)
                self._landing[get_id] = zone
                self._frag_active += 1
            if data is not None:
                if offset in zone.landed:
                    self.dup_frags += 1
                    return
                zone.landed.add(offset)
        if data is not None:
            # in-process tiers: the fragment view is copied into the final
            # destination here, interleaved with task execution; on the
            # socket tier the recv thread already landed the bytes
            self._zone_write(zone, offset, data)
        zone.remaining -= nbytes
        self.frags_in += 1
        self.frag_bytes_in += nbytes
        pins.fire(PinsEvent.COMM_GET_FRAG_RECV, None, nbytes)
        self.send_am(AM_TAG_GET_FRAG_ACK, src, (get_id,))
        if zone.remaining > 0:
            return
        with self._frag_lock:
            self._landing.pop(get_id, None)
            self._frag_active -= 1
        value = self._land_value(self._zone_finish(zone))
        pins.fire(PinsEvent.COMM_GET_DONE, None, int(zone.meta["nbytes"]))
        self._record_get_span(get_id, int(zone.meta["nbytes"]))
        cb = self._pending_gets.pop(get_id, None)
        if cb is None:
            self.dup_get_replies += 1
            return
        cb(value)

    def frag_state(self) -> dict:
        """In-flight fragmentation state (flight-recorder stall dumps)."""
        with self._frag_lock:
            return {"landing_zones": len(self._landing),
                    "frag_sends": len(self._frag_sends),
                    "frags_in": self.frags_in,
                    "frag_bytes_in": self.frag_bytes_in,
                    "frags_out": self.frags_out,
                    "frag_bytes_out": self.frag_bytes_out,
                    "dup_frags": self.dup_frags,
                    "prefetch_gets": self.prefetch_gets}

    def on_peer_failed(self, rank: int) -> int:
        # a dead consumer's open send windows are abandoned (its credit
        # acks will never arrive), and a dead OWNER's landing zones are
        # dropped — leaking either would pin _frag_active nonzero and the
        # busy-worker progress gate would fire forever.  (The pending-get
        # callback stays unresolved, exactly like a monolithic GET_REPLY
        # that will never arrive: context failure handling owns that.)
        with self._frag_lock:
            for key in [k for k in self._frag_sends if k[0] == rank]:
                del self._frag_sends[key]
                self._frag_active -= 1
            for gid in [g for g, z in self._landing.items()
                        if z.src == rank]:
                del self._landing[gid]
                self._frag_active -= 1
        return super().on_peer_failed(rank)

    # -- progress -------------------------------------------------------------
    def pending(self) -> int:
        return self.fabric.pending(self.rank)

    def progress(self) -> int:
        # funnelled discipline: idle workers, quiesce, and rank threads may
        # all race here — only one thread drives the engine at a time, the
        # rest skip (non-blocking) so AM callbacks never interleave
        if not self._progress_lock.acquire(blocking=False):
            return 0
        try:
            n = 0
            if self.flush_hook is not None:
                n += self.flush_hook()
            for tag, src, payload in self.fabric.drain(self.rank):
                cb = self._am_callbacks.get(tag)
                if cb is None:
                    raise RuntimeError(f"no callback for AM tag {tag}")
                cb(self, src, payload)
                n += 1
            return n
        finally:
            self._progress_lock.release()

    def _on_barrier(self, eng: CommEngine, src: int, msg: dict) -> None:
        self._barrier_seen.setdefault(msg["gen"], set()).add(src)

    def sync(self) -> None:
        """All-ranks barrier over AMs, progressing while waiting."""
        import time
        gen = self._barrier_gen = self._barrier_gen + 1
        seen = self._barrier_seen.setdefault(gen, set())
        for r in range(self.nranks):
            if r != self.rank:
                self.send_am(AM_TAG_BARRIER, r, {"gen": gen})
        deadline = time.monotonic() + 30.0
        while len(seen) < self.nranks - 1:
            self.progress()
            if time.monotonic() > deadline:
                raise TimeoutError(f"rank {self.rank} barrier timeout")
        del self._barrier_seen[gen]
