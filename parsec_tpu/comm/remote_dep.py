"""Remote dependency activation: release_deps across ranks.

Rebuild of ``remote_dep.c`` / ``remote_dep_mpi.c`` (SURVEY §3.4):

- the producer's ``release_deps`` accumulates per-output **rank bitmaps**
  into a :class:`RemoteDeps` record (``parsec_remote_deps_t``,
  ``remote_dep.h:132-153``) instead of releasing locally;
- :meth:`RemoteDepEngine.activate` packs a wire activation
  {taskpool comm-id, task-class id, locals, output mask, payload
  descriptors} (``remote_dep_wire_activate_t``, ``remote_dep.h:42-50``),
  **inlines short payloads** (``remote_dep_mpi_pack_dep:1270``), registers
  larger ones for rendezvous GET, and sends it down a **propagation tree**
  (binomial / chain / star, ``remote_dep.c:320-358``) re-derived
  deterministically at each hop from the sorted participant list;
- the receiver reconstructs the *ghost predecessor task* and re-runs its
  successor iterator restricted to this rank to learn where each payload
  lands (``remote_dep_get_datatypes:820``), pulls non-inline payloads
  (``remote_dep_mpi_get_start:2042``), then releases local successors into
  the scheduler (``remote_dep_release_incoming:955``) and forwards to its
  tree children (``parsec_remote_dep_propagate:409``);
- every in-flight activation holds a **pending action** on the producing
  taskpool's termination detector, dropped when the consumer acknowledges
  (``remote_dep_dec_flying_messages``, ``remote_dep.h:367-372``).

Writeback edges (``-> A(k)`` arrows whose home tile lives on another rank)
ride the same activation with an ownerless descriptor; the home rank applies
them to its local master copy.

TPU-first note: on hardware the payload move is an ICI device-to-device
transfer between HBM-resident tiles; the tree propagation maps onto neighbor
chains of the ICI torus.  The in-process fabric exercises the identical
protocol (SURVEY §5.8).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any

import numpy as np

from ..core.params import params as _params
from ..data.data import data_create
from ..data.datatype import wire_slice_key
from ..prof import pins, spans as _spans
from ..prof.pins import PinsEvent

_now_ns = time.perf_counter_ns
from ..runtime.scheduling import (ExecutionStream, _find_input_dep,
                                  apply_writeback_to_home, schedule_tasks)
from ..runtime.task import Task
from .engine import (AM_TAG_ACTIVATE, AM_TAG_DTD, AM_TAG_GET_ACK,
                     AM_TAG_TERMDET, CommEngine)

_params.register("comm_short_limit", 4096,
                 "payloads at most this many bytes ride inside the "
                 "activation message (short-message inlining)")
_params.register("comm_thread", False,
                 "run a dedicated comm-progress thread per rank "
                 "(remote_dep_dequeue_main analog)")
_params.register("comm_coalesce", True,
                 "stage outgoing activations and flush one "
                 "priority-ordered AM per peer per progress "
                 "(remote_dep_mpi.c:1066-1194 aggregation)")
_params.register("comm_wire_datatypes", True,
                 "honor partial-tile wire datatypes ([type_remote/"
                 "displ_remote]) on remote edges; off ships full tiles")
_params.register("comm_bcast_tree", "binomial",
                 "multi-peer activation propagation: binomial|chain|star, "
                 "or auto (per-payload: resolve_tree_kind)")
_params.declare_knob("comm_bcast_tree",
                     values=("binomial", "chain", "star", "auto"))


def _wire_value(value: Any) -> Any:
    """Normalize a payload for the wire: JAX arrays stay device-resident
    (immutable — the device transport moves them D2D); everything else
    becomes a host ndarray."""
    from .device_fabric import is_device_array
    if is_device_array(value):
        return value
    return np.asarray(value)


def _slice_view(value: Any, view_key: tuple) -> Any:
    """Cut the wire view out of a tile (host or device array).  The copy
    is unconditional for host arrays (``ascontiguousarray`` would alias
    when the slice happens to be contiguous — e.g. 1-row tiles): the
    wire must not alias the live tile a local successor may be mutating.
    An out-of-range view is an error, not a silent clamp — numpy would
    ship a SMALLER region and the consumer's shape branch would
    misclassify it."""
    sl = []
    for axis, s in enumerate(view_key):
        s = slice(*s) if isinstance(s, (tuple, list)) else s
        if isinstance(s, slice) and s.stop is not None \
                and s.stop > value.shape[axis]:
            raise ValueError(
                f"wire view {view_key} exceeds tile shape {value.shape} "
                f"on axis {axis} (bad displ_remote?)")
        sl.append(s)
    out = value[tuple(sl)]
    if isinstance(out, np.ndarray):
        out = np.array(out, copy=True)
    return out


# ---------------------------------------------------------------------------
# compact activation wire form: coalesced batches used to ship as nested
# dicts (str keys repeated per message, per output, per batch entry); the
# positional tuples below cut the meta the codec has to walk and emit to a
# few dozen bytes per activation.  Inline ndarray payloads ride as raw
# codec segments either way — this trims the *structure*, the codec already
# removed the pickling of the *bytes*.
# ---------------------------------------------------------------------------

_OPT_DESC_KEYS = ("version", "inline", "wire", "shape", "dtype", "wire_view")


def _pack_desc(d: dict) -> tuple:
    flags = 0
    vals = []
    for i, k in enumerate(_OPT_DESC_KEYS):
        if k in d:
            flags |= 1 << i
            vals.append(d[k])
    return (d["flow_index"], 1 if d.get("writeback") else 0, flags, *vals)


def _unpack_desc(t: tuple) -> dict:
    d = {"flow_index": t[0], "writeback": bool(t[1])}
    flags, j = t[2], 3
    for i, k in enumerate(_OPT_DESC_KEYS):
        if flags & (1 << i):
            d[k] = t[j]
            j += 1
    return d


def pack_activation(msg: dict) -> tuple:
    """dict activation → positional wire tuple (tag "A").  The trailing
    element is the request's 8-byte trace context (prof/spans.py; 0 =
    untraced) — the cross-rank propagation of request-scoped tracing."""
    return ("A", msg["tp"], msg["tc"], msg["locals"],
            [_pack_desc(d) for d in msg["outputs"]], msg["ranks"],
            msg["tree"], msg["priority"], msg["seq"], msg["pos"],
            msg.get("trace") or 0)


def unpack_activation(t: tuple) -> dict:
    return {"tp": t[1], "tc": t[2], "locals": t[3],
            "outputs": [_unpack_desc(x) for x in t[4]], "ranks": t[5],
            "tree": t[6], "priority": t[7], "seq": t[8], "pos": t[9],
            # mixed-version peers may still ship the 10-element form
            "trace": t[10] if len(t) > 10 else 0}


# ---------------------------------------------------------------------------
# propagation trees (cf. remote_dep.c:320-358) — positions are indices into
# the sorted participant list, position 0 = root; children are re-derived
# identically at every hop, so no child list rides the wire
# ---------------------------------------------------------------------------

def _packed_trace(m: Any) -> int:
    """The trace id of one staged activation (packed tuple element 10;
    0 for legacy/test payloads that never carried one)."""
    if type(m) is tuple and len(m) > 10 and type(m[10]) is int:
        return m[10]
    return 0


TREE_KINDS = ("binomial", "chain", "star")


def _check_tree_kind(kind: str) -> None:
    if kind not in TREE_KINDS:
        from ..core.params import MCAParamValueError
        raise MCAParamValueError("comm_bcast_tree", kind, TREE_KINDS)


def resolve_tree_kind(kind: str | None = None, *,
                      nbytes: int | None = None,
                      n: int | None = None) -> str:
    """Resolve a tree-shape request (the ``comm_bcast_tree`` param when
    ``kind`` is None) to a concrete member of :data:`TREE_KINDS`.

    ``auto`` picks per payload class: payloads at or under
    ``comm_short_limit`` on small meshes (≤8 participants) take the
    latency-minimal star — they ride inline in the activation frame, so
    root egress is one frame per peer either way; everything else takes
    the egress-bounding binomial (the root re-serves at most ⌈log2 n⌉
    copies).  ``analysis/commcheck.recommend_tree`` derives its
    per-edge-class shapes through this same rule, so static advice and
    runtime resolution cannot drift.

    The wire never carries ``auto``: activation staging resolves once
    per message and ships the concrete kind, since every hop re-derives
    its children from ``msg["tree"]``."""
    if kind is None:
        kind = _params.get("comm_bcast_tree")
    if kind == "auto":
        if nbytes is not None and \
                0 < nbytes <= _params.get("comm_short_limit") \
                and (n if n is not None else 2) <= 8:
            return "star"
        return "binomial"
    _check_tree_kind(kind)
    return kind


def tree_children(kind: str, position: int, n: int) -> list[int]:
    _check_tree_kind(kind)
    if n <= 1:
        return []
    if kind == "star":
        return list(range(1, n)) if position == 0 else []
    if kind == "chain":
        return [position + 1] if position + 1 < n else []
    # binomial: children of p are p + 2^j for 2^j > p
    out = []
    j = 1
    while j <= position:
        j <<= 1
    while position + j < n:
        out.append(position + j)
        j <<= 1
    return out


def tree_parent(kind: str, position: int, n: int) -> int | None:
    """The inverse of :func:`tree_children`: the position that re-serves
    payloads to ``position`` (``None`` for the root).  Binomial parent =
    the position with its most-significant set bit cleared."""
    _check_tree_kind(kind)
    if position <= 0 or n <= 1:
        return None
    if kind == "star":
        return 0
    if kind == "chain":
        return position - 1
    return position & ~(1 << (position.bit_length() - 1))


# ---------------------------------------------------------------------------
# producer-side accumulation
# ---------------------------------------------------------------------------

class _RemoteOutput:
    __slots__ = ("flow_index", "copy", "ranks", "writeback_ranks", "views")

    def __init__(self, flow_index: int) -> None:
        self.flow_index = flow_index
        self.copy = None              # producing DataCopy (None for CTL)
        self.ranks: set[int] = set()  # ranks with consumer successors
        self.writeback_ranks: set[int] = set()  # remote home-tile writebacks
        # rank -> wire view key (slice triples) | None (full tile): the
        # partial-tile wire datatypes of the edges that reached that rank
        # ([type_remote/displ_remote]); a rank touched by several edges
        # with DIFFERENT views degrades to the full tile (the superset is
        # always correct; the reference picks one dep's datatype per rank)
        self.views: dict[int, tuple | None] = {}


class RemoteDeps:
    """Per-completed-task record of which peers need which outputs."""

    __slots__ = ("task", "outputs")

    def __init__(self, task: Task) -> None:
        self.task = task
        self.outputs: dict[int, _RemoteOutput] = {}

    def output(self, flow_index: int) -> _RemoteOutput:
        o = self.outputs.get(flow_index)
        if o is None:
            o = self.outputs[flow_index] = _RemoteOutput(flow_index)
        return o


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class RemoteDepEngine:
    """Owns one rank's comm engine and implements the activation protocol.

    Installed as ``context.comm_engine``; the context delegates
    ``remote_dep_accumulate`` / ``remote_dep_activate`` here and calls
    :meth:`progress` from idle workers (the reference funnels the same work
    through its comm thread, ``remote_dep_mpi.c:426-484``).
    """

    def __init__(self, context: Any, ce: CommEngine) -> None:
        self.ctx = context
        self.ce = ce
        context.comm_engine = self
        self.my_rank = ce.rank
        self.nranks = ce.nranks
        self._es = ExecutionStream(-2, context.virtual_processes[0], context)
        self._seq = itertools.count(1)
        # outgoing activation stage: per-peer pending lists flushed by
        # progress (or the dedicated comm thread) as ONE coalesced AM per
        # peer, priority-ordered — the dep_cmd_queue aggregation of
        # remote_dep_mpi.c:1066-1194
        self._outq: dict[int, list] = {}
        self._outq_lock = threading.Lock()
        # serializes whole drains of the outgoing stage: concurrent callers
        # (worker via _flush_if_unthreaded, comm thread, engine flush hook)
        # would otherwise interleave their per-peer sends and break the
        # highest-priority-first ordering across snapshots
        self._flush_serial = threading.Lock()
        self._outseq = itertools.count()
        self._comm_thread: threading.Thread | None = None
        self._comm_stop: threading.Event | None = None
        # activation seq -> (taskpool, parent_rank or None)
        self._inflight: dict[int, Any] = {}
        self._iflock = threading.Lock()
        self.dup_acks = 0      # duplicate/unknown acks tolerated (faults)
        # activation payload bytes staged by THIS rank as a bcast root
        # (post wire-view slicing; counted once per receiving peer) — the
        # counter that proves partial-tile wire datatypes cut halo
        # traffic (~NB/R for the stencil's LR edges)
        self.payload_bytes_staged = 0
        # activations/DTD messages whose taskpool comm-id is not registered
        # yet (cf. DEP_NEW_TASKPOOL delays, remote_dep_mpi.c); guarded by a
        # lock: appended from worker progress, replayed from the enqueuing
        # thread; entries are (handler, src, msg)
        self._pending_unknown_tp: list[tuple[Any, int, dict]] = []
        self._pending_lock = threading.Lock()
        # distributed termdet monitors by taskpool comm-id, + stashed tokens
        self._termdet: dict[int, Any] = {}
        self._pending_termdet: list[dict] = []
        # received activation payload bytes (the inbound counterpart of
        # payload_bytes_staged; both are snapshotter-sampled gauges)
        self.payload_bytes_received = 0
        ce.tag_register(AM_TAG_ACTIVATE, self._on_activate)
        ce.tag_register(AM_TAG_GET_ACK, self._on_ack)
        ce.tag_register(AM_TAG_TERMDET, self._on_termdet)
        ce.tag_register(AM_TAG_DTD, self._on_dtd)
        # every engine progress drives the outgoing stage too — loops that
        # spin on raw ce.progress() (sync, quiesce) must flush forwards
        # their own AM handlers stage mid-wait
        ce.flush_hook = self.flush_outgoing
        from ..prof.counters import properties, sde
        sde.register_gauge(f"comm::rank{self.my_rank}::inflight",
                           self.inflight)
        sde.register_gauge(f"comm::rank{self.my_rank}::bytes_out",
                           lambda: self.payload_bytes_staged)
        sde.register_gauge(f"comm::rank{self.my_rank}::bytes_in",
                           lambda: self.payload_bytes_received)
        # wire-level twins of the payload counters: total framed bytes the
        # fabric moved each way, plus the fragment pipeline's own counters
        fabric = getattr(ce, "fabric", None)
        sde.register_gauge(f"comm::rank{self.my_rank}::wire_bytes_out",
                           lambda: getattr(fabric, "bytes_sent", 0))
        sde.register_gauge(f"comm::rank{self.my_rank}::wire_bytes_in",
                           lambda: getattr(fabric, "bytes_recv", 0))
        sde.register_gauge(f"comm::rank{self.my_rank}::frags_in",
                           lambda: getattr(ce, "frags_in", 0))
        sde.register_gauge(f"comm::rank{self.my_rank}::frag_bytes_in",
                           lambda: getattr(ce, "frag_bytes_in", 0))
        # per-peer bytes/frames/frags ledgers (socket tier) + fragment
        # pipeline state, as one live property the snapshotter samples
        properties.register("comm", f"rank{self.my_rank}",
                            self._comm_property)

    # ------------------------------------------------------------ lifecycle
    def enable(self) -> None:
        self.ce.enable()
        if _params.get("comm_thread") and self._comm_thread is None:
            # the dedicated progress thread of remote_dep_mpi.c's
            # remote_dep_dequeue_main: owns flushing + draining so workers
            # never stall on comm (they may still opportunistically
            # progress; the engine's internal lock keeps it single-driver)
            self._comm_stop = threading.Event()
            self._comm_thread = threading.Thread(
                target=self._comm_main, daemon=True,
                name=f"parsec-comm-r{self.my_rank}")
            self._comm_thread.start()

    def _comm_main(self) -> None:
        from ..core.backoff import Backoff
        backoff = Backoff()
        while not self._comm_stop.is_set():
            try:
                n = self.flush_outgoing() + self.ce.progress()
            except BaseException as e:   # surface like a worker failure:
                # a silent dead comm thread is a hang, not a crash
                self.ctx.record_failure(e)
                return
            if n:
                backoff.reset()
            else:
                backoff.wait()

    def fini(self) -> None:
        if self._comm_thread is not None:
            self._comm_stop.set()
            self._comm_thread.join(timeout=5)
            self._comm_thread = None
        self.flush_outgoing()
        self.ce.fini()
        from ..prof.counters import properties, sde
        for g in ("inflight", "bytes_out", "bytes_in", "wire_bytes_out",
                  "wire_bytes_in", "frags_in", "frag_bytes_in"):
            sde.unregister_gauge(f"comm::rank{self.my_rank}::{g}")
        properties.unregister("comm", f"rank{self.my_rank}")

    def _comm_property(self) -> dict:
        """The ``comm`` block of the live properties dictionary: fragment
        pipeline state plus per-peer wire ledgers when the fabric keeps
        them (docs/COMM.md)."""
        out: dict = {}
        fs = getattr(self.ce, "frag_state", None)
        if fs is not None:
            out.update(fs())
        ps = getattr(getattr(self.ce, "fabric", None), "peer_stats", None)
        if ps is not None:
            out["peers"] = ps()
        return out

    def debug_state(self) -> dict:
        """In-flight comm operations for the flight-recorder stall dump."""
        with self._outq_lock:
            staged = {dst: len(items) for dst, items in self._outq.items()}
        with self._iflock:
            inflight = len(self._inflight)
        with self._pending_lock:
            unknown = len(self._pending_unknown_tp)
            pending_td = len(self._pending_termdet)
        return {"rank": self.my_rank, "inflight_activations": inflight,
                "staged_sends": staged, "pending_unknown_taskpool": unknown,
                "pending_termdet_tokens": pending_td,
                "dup_acks": self.dup_acks,
                "payload_bytes_staged": self.payload_bytes_staged,
                "payload_bytes_received": self.payload_bytes_received,
                "engine_pending": self.ce.pending(),
                "comm_thread": self._comm_thread is not None,
                **self._comm_property()}

    def progress(self, es: Any = None) -> int:
        # the engine's progress drives flush_outgoing through flush_hook,
        # so one call covers both halves (no double drain)
        return self.ce.progress()

    # -------------------------------------------- outgoing stage (coalescing)
    def _post_activate(self, dst: int, msg: dict) -> None:
        # well-formed activations ride the compact positional form; other
        # dicts (tests driving the staging queue directly) pass through
        packed = pack_activation(msg) if "tp" in msg else msg
        if not _params.get("comm_coalesce"):
            self.ce.send_am(AM_TAG_ACTIVATE, dst, packed,
                            trace_id=_packed_trace(packed))
            return
        with self._outq_lock:
            self._outq.setdefault(dst, []).append(
                (-msg.get("priority", 0), next(self._outseq), packed))

    def _flush_if_unthreaded(self) -> None:
        """The staging queue is the comm thread's mailbox; without one,
        flush at the end of each send batch so busy workers never starve
        outgoing sends (coalescing still aggregates within the batch)."""
        if self._comm_thread is None:
            self.flush_outgoing()

    def flush_outgoing(self) -> int:
        """Drain the outgoing stage: one AM per peer, messages inside
        ordered highest-priority-first (the same-peer aggregation +
        priority ordering of remote_dep_mpi.c:1066-1194).  Whole drains are
        serialized so the priority contract holds globally, not merely
        per-snapshot, when multiple progress paths flush at once."""
        if not self._outq:
            return 0
        with self._flush_serial:
            with self._outq_lock:
                batches, self._outq = self._outq, {}
            n = 0
            for dst, items in batches.items():
                items.sort(key=lambda it: it[:2])
                msgs = [m for _, _, m in items]
                if len(msgs) == 1:
                    # a lone activation's trace context rides the frame
                    # header too (CTRL u2); coalesced aggregates mix
                    # requests, so their header word stays 0 and the
                    # per-message trace fields carry it instead
                    self.ce.send_am(AM_TAG_ACTIVATE, dst, msgs[0],
                                    trace_id=_packed_trace(msgs[0]))
                else:
                    # coalesced same-peer aggregate: a flat positional
                    # batch, no nested per-message dicts on the wire
                    self.ce.send_am(AM_TAG_ACTIVATE, dst, ("B", msgs))
                n += len(msgs)
        return n

    def inflight(self) -> int:
        with self._iflock:
            return len(self._inflight)

    def quiesce(self, timeout: float = 60.0) -> None:
        """Progress until this rank has no in-flight activations and an
        all-ranks barrier passes twice with silence in between (context-level
        drain; taskpool-level termination is the termdet's job)."""
        import time
        deadline = time.monotonic() + timeout
        for _round in range(2):
            while self.inflight() or self.ce.pending() or self._outq:
                self.progress()
                if time.monotonic() > deadline:
                    raise TimeoutError(f"rank {self.my_rank} quiesce timeout")
            self.ce.sync()

    # ------------------------------------------------- producer (sender) side
    def accumulate(self, remote: RemoteDeps | None, task: Task, flow, dep,
                   succ_tc, succ_locals, rank: int) -> RemoteDeps:
        """One remote successor edge found by release_deps (the remote branch
        of ``parsec_release_dep_fct``, ``parsec.c:1808-1874``)."""
        if remote is None:
            remote = RemoteDeps(task)
        out = remote.output(flow.flow_index)
        if not flow.is_ctl:
            out.copy = task.data[flow.flow_index]
        if succ_tc is None:
            # home-tile writeback must carry the whole tile
            out.writeback_ranks.add(rank)
            out.views[rank] = None
        else:
            out.ranks.add(rank)
            vk = (wire_slice_key(dep.wire_slices(task.locals))
                  if _params.get("comm_wire_datatypes") else None)
            if rank in out.views and out.views[rank] != vk:
                out.views[rank] = None     # conflicting views: full tile
            else:
                out.views.setdefault(rank, vk)
        return remote

    def activate(self, es: Any, task: Task, remote: RemoteDeps) -> None:
        """Kick the sends (``parsec_remote_dep_activate``, ``remote_dep.c:441``).

        Peers are grouped by identical output masks so true broadcasts share
        one propagation tree; odd one-off masks fall back to direct sends.
        """
        tp = task.taskpool
        # group peers by (flow set + per-flow wire view): ranks receiving
        # identical bytes share one propagation tree; a partial-tile view
        # ([type_remote]) forms its own group so the sliced payload is cut
        # once and broadcast, never re-sliced per peer
        by_mask: dict[tuple, list[int]] = {}
        all_ranks: dict[int, set[int]] = {}
        for fi, out in remote.outputs.items():
            for r in out.ranks | out.writeback_ranks:
                all_ranks.setdefault(r, set()).add(fi)
        for r, flows in all_ranks.items():
            key = tuple((fi, remote.outputs[fi].views.get(r))
                        for fi in sorted(flows))
            by_mask.setdefault(key, []).append(r)

        for flows, ranks in by_mask.items():
            ranks.sort()
            # resolve the tree shape ONCE per activation message (the
            # wire never carries "auto" — every hop re-derives children
            # from msg["tree"]); the hint is the largest staged payload
            hint = max((int(getattr(remote.outputs[fi].copy.value,
                                    "nbytes", 0))
                        for fi, _v in flows
                        if remote.outputs[fi].copy is not None),
                       default=0)
            tree_kind = resolve_tree_kind(nbytes=hint, n=len(ranks) + 1)
            outputs = []
            for fi, view in flows:
                out = remote.outputs[fi]
                desc = {"flow_index": fi,
                        "writeback": bool(out.writeback_ranks)}
                if out.copy is not None:
                    value = _wire_value(out.copy.value)
                    owned = False
                    if view is not None:
                        # partial-tile wire datatype: ship only the
                        # declared sub-block (the LR ghost columns, not
                        # the whole tile); the consumer receives it as a
                        # standalone region buffer
                        value = _slice_view(value, view)   # owned copy
                        desc["wire_view"] = view
                        owned = True
                    self.payload_bytes_staged += int(
                        getattr(value, "nbytes", 0)) * len(ranks)
                    desc["version"] = out.copy.version
                    if value.nbytes <= _params.get("comm_short_limit"):
                        # receiver must own its bytes even in-process
                        # (immutable device arrays ride as-is; a sliced
                        # view was already cut to an owned buffer)
                        desc["inline"] = (value.copy()
                                          if isinstance(value, np.ndarray)
                                          and not owned else value)
                    else:
                        all_ranks = [self.my_rank] + ranks
                        child_ranks = [
                            all_ranks[p] for p in tree_children(
                                tree_kind, 0, len(all_ranks))]
                        # snapshot at registration: a local successor may
                        # mutate the live host tile in place before the
                        # remote GET is served (the reference retains a
                        # refcounted data copy for the whole send); the
                        # engine copies mutable buffers at the boundary.
                        # peers= lets a dead child's share be reclaimed.
                        h = self.ce.mem_register(value,
                                                 refcount=len(child_ranks),
                                                 peers=set(child_ranks))
                        desc["wire"] = h.wire()
                        desc["shape"] = value.shape
                        desc["dtype"] = str(value.dtype)
                outputs.append(desc)
            tr = getattr(tp, "_trace", None)
            msg = {
                "tp": tp.comm_id,
                "tc": task.task_class.task_class_id,
                "locals": dict(task.locals),
                "outputs": outputs,
                # participants: producer at position 0, consumers after —
                # every hop re-derives its children from this list
                "ranks": [self.my_rank] + ranks,
                "tree": tree_kind,
                "priority": task.priority,
                # the request's 8-byte trace context rides every hop of
                # the propagation tree (prof/spans.py; 0 = untraced)
                "trace": tr.trace_id if tr is not None else 0,
            }
            self._send_to_children(tp, msg, my_pos=0)
        self._flush_if_unthreaded()

    def _send_to_children(self, tp: Any, msg: dict, my_pos: int) -> None:
        ranks = msg["ranks"]
        for child_pos in tree_children(msg["tree"], my_pos, len(ranks)):
            seq = next(self._seq)
            with self._iflock:
                self._inflight[seq] = tp
            # in-flight activation == pending action on the termdet
            # (remote_dep.h:360-372); fourcounter also counts raw messages
            tp.tdm.taskpool_addto_nb_pa(+1)
            tp.tdm.on_comm_sent()
            child_msg = dict(msg)
            child_msg["seq"] = seq
            child_msg["pos"] = child_pos
            pins.fire(PinsEvent.COMM_ACTIVATE_SEND, None,
                      (ranks[child_pos], seq))
            r = _spans.recorder
            if r is not None and msg.get("trace"):
                # the emit half of one activation hop: tracemerge
                # stitches it to the child rank's recv span by flow id
                t = _now_ns()
                r.record("comm.activate", msg["trace"], t, t,
                         args={"flow": f"act:{self.my_rank}:{seq}",
                               "flow_side": "emit",
                               "dst": ranks[child_pos]})
            self._post_activate(ranks[child_pos], child_msg)

    def _on_ack(self, eng, src: int, msg: dict) -> None:
        pins.fire(PinsEvent.COMM_ACK_RECV, None, int(msg["seq"]))
        with self._iflock:
            tp = self._inflight.pop(msg["seq"], None)
        if tp is None:
            # duplicate or unknown ack (transport replay after a reconnect,
            # or a peer acking twice): the first landing already settled the
            # pending-action count — tolerate, count, move on
            self.dup_acks += 1
            return
        tp.tdm.taskpool_addto_nb_pa(-1)

    # ------------------------------------------------- consumer (receiver) side
    # --------------------------------------------------- distributed termdet
    def send_termdet(self, dst: int, token: dict) -> None:
        """Ship a termination-detection token (reserved tag, §2.4/§2.6)."""
        self.ce.send_am(AM_TAG_TERMDET, dst, token)

    def _on_termdet(self, eng, src: int, token: dict) -> None:
        mon = self._termdet.get(token["tp"])
        if mon is None:
            tp = self.ctx._tp_by_comm_id.get(token["tp"])
            if tp is not None:
                raise RuntimeError(
                    f"rank {self.my_rank}: termdet wave token for taskpool "
                    f"{tp.name} whose detector ({tp.tdm.name}) is not "
                    f"distributed — termdet selection differs across ranks")
            with self._pending_lock:
                mon = self._termdet.get(token["tp"])
                if mon is None:
                    self._pending_termdet.append(token)
                    return
        mon.on_token(token)

    def taskpool_registered(self, tp: Any) -> None:
        """Replay activations/tokens that raced ahead of the enqueue."""
        distributed = hasattr(tp.tdm, "on_token")
        with self._pending_lock:
            if distributed:
                self._termdet[tp.comm_id] = tp.tdm
            replay_td = [t for t in self._pending_termdet
                         if t["tp"] == tp.comm_id]
            self._pending_termdet = [
                t for t in self._pending_termdet if t["tp"] != tp.comm_id]
            replay = [m for m in self._pending_unknown_tp
                      if m[2]["tp"] == tp.comm_id]
            self._pending_unknown_tp = [
                m for m in self._pending_unknown_tp
                if m[2]["tp"] != tp.comm_id]
        if replay_td and not distributed:
            raise RuntimeError(
                f"rank {self.my_rank}: received termdet wave tokens for "
                f"taskpool {tp.name} whose detector ({tp.tdm.name}) is not "
                f"distributed — termdet selection differs across ranks")
        for token in replay_td:
            tp.tdm.on_token(token)
        for handler, src, msg in replay:
            handler(self.ce, src, msg)

    def _lookup_or_pend(self, handler, src: int, msg: dict):
        tp = self.ctx._tp_by_comm_id.get(msg["tp"])
        if tp is None:
            with self._pending_lock:
                # re-check under the lock: registration may have just landed
                tp = self.ctx._tp_by_comm_id.get(msg["tp"])
                if tp is None:
                    self._pending_unknown_tp.append((handler, src, msg))
        return tp

    # ------------------------------------------------ DTD cross-rank channel
    def dtd_send(self, tp: Any, dst: int, msg: dict) -> None:
        """Ship a DTD protocol message (tile push / flush) to ``dst``,
        holding a termdet pending action until the ack lands (the
        DEP_DTD_DELAYED_RELEASE-era accounting, ``remote_dep_mpi.c:2022``)."""
        seq = next(self._seq)
        with self._iflock:
            self._inflight[seq] = tp
        tp.tdm.taskpool_addto_nb_pa(+1)
        tp.tdm.on_comm_sent()
        self.ce.send_am(AM_TAG_DTD, dst, dict(msg, tp=tp.comm_id, seq=seq))

    def _on_dtd(self, eng, src: int, msg: dict) -> None:
        tp = self._lookup_or_pend(self._on_dtd, src, msg)
        if tp is None:
            return
        tp.tdm.on_comm_recv()
        tp._on_dtd_message(self, src, msg)
        self.ce.send_am(AM_TAG_GET_ACK, src, {"seq": msg["seq"]})

    def _on_activate(self, eng, src: int, msg: Any) -> None:
        if type(msg) is tuple:
            if msg[0] == "B":
                # a coalesced aggregate: unpack in (priority) order
                for m in msg[1]:
                    self._on_activate(eng, src, m)
                return
            msg = unpack_activation(msg)
        elif "batch" in msg:
            # legacy dict aggregate (tests / mixed-version peers)
            for m in msg["batch"]:
                self._on_activate(eng, src, m)
            return
        tp = self._lookup_or_pend(self._on_activate, src, msg)
        if tp is None:
            return
        pins.fire(PinsEvent.ACTIVATE_CB_BEGIN, None, (src, msg["seq"]))
        want = [d for d in msg["outputs"] if "wire" in d]
        # every receiver owns its bytes: an inline payload forwarded down the
        # tree would otherwise alias across ranks
        landed: dict[int, Any] = {
            d["flow_index"]: (d["inline"].copy()
                              if isinstance(d["inline"], np.ndarray)
                              else d["inline"])
            for d in msg["outputs"] if "inline" in d}
        if not want:
            self._complete_incoming(tp, src, msg, landed)
            return
        remaining = [len(want)]

        def make_cb(d):
            def cb(value):
                landed[d["flow_index"]] = value
                remaining[0] -= 1
                if remaining[0] == 0:
                    self._complete_incoming(tp, src, msg, landed)
            return cb

        for d in want:
            # the GET inherits the activation's trace context, so both
            # ends of the rendezvous span-record under the request
            self.ce.get(tuple(d["wire"]), make_cb(d),
                        trace=msg.get("trace") or None)

    def _complete_incoming(self, tp: Any, src: int, msg: dict,
                           landed: dict[int, Any]) -> None:
        """All payloads present: release local successors, apply writebacks,
        forward down the tree, ack the parent."""
        t0 = _now_ns() if _spans.recorder is not None else 0
        for v in landed.values():
            self.payload_bytes_received += int(getattr(v, "nbytes", 0))
        tp.tdm.on_comm_recv()
        tc = tp.task_classes[msg["tc"]]
        ghost = Task(tp, tc, dict(msg["locals"]),
                     priority=msg.get("priority", 0))
        copies = {}
        for d in msg["outputs"]:
            fi = d["flow_index"]
            if fi in landed:
                datum = data_create(
                    landed[fi], key=("remote", tp.comm_id, tc.name,
                                     tuple(sorted(msg["locals"].items())), fi))
                copy = datum.get_copy(0)
                copy.version = d.get("version", 1)
                copies[fi] = copy
                ghost.data[fi] = copy

        ready: list[Task] = []
        out_mask = {d["flow_index"] for d in msg["outputs"]}
        wb = {d["flow_index"]: d.get("writeback", False)
              for d in msg["outputs"]}

        from ..data.reshape import reshape_for_edge, reshape_for_writeback

        def visitor(t: Task, flow, dep) -> None:
            if flow.flow_index not in out_mask:
                return
            if dep.target_class is None:
                # apply only on the tile's home rank: other ranks sharing
                # this activation's mask must not fabricate master copies
                if wb.get(flow.flow_index) and dep.data_ref is not None:
                    copy = copies.get(flow.flow_index)
                    dc, key = dep.data_ref(t.locals)
                    if copy is not None and dc.rank_of(*key) == self.my_rank:
                        copy = reshape_for_writeback(copy, dep, dc, key)
                        apply_writeback_to_home(dc, key, copy,
                                                owner=tp.taskpool_id)
                return
            succ_tc = tp.task_class(dep.target_class)
            for succ_locals in dep.each_target(t.locals):
                if succ_tc.in_space is not None \
                        and not succ_tc.in_space(succ_locals):
                    continue   # generated bounds check, receiver side
                rank = self._succ_rank(succ_tc, succ_locals)
                if rank != self.my_rank:
                    continue
                fi, di = _find_input_dep(succ_tc, dep.target_flow, tc.name,
                                         succ_locals)
                # the wire carries the producer's type; a typed edge
                # repacks on the read side (remote_dep.h:102-113 dtt_dst
                # over dtt_src), lazily and shared per (copy, type)
                send = reshape_for_edge(copies.get(flow.flow_index), dep,
                                        succ_tc.flows[fi].deps_in[di])
                rt = self.ctx.deps.release_dep(tp, succ_tc, succ_locals, fi,
                                               di, send, None)
                if rt is not None:
                    ready.append(rt)

        tc.iterate_successors(ghost, visitor)

        # interior tree node: re-register landed buffers and forward
        # (parsec_remote_dep_propagate, remote_dep.c:409-436)
        my_pos = msg["pos"]
        children = tree_children(msg["tree"], my_pos, len(msg["ranks"]))
        if children:
            fwd = dict(msg)
            fwd["outputs"] = [dict(d) for d in msg["outputs"]]
            for d in fwd["outputs"]:
                if "wire" in d:
                    # snapshot: the landed host buffer is simultaneously
                    # handed to local successors, which may mutate it in
                    # place (the engine copies mutable buffers; device
                    # arrays are immutable and alias)
                    value = _wire_value(landed[d["flow_index"]])
                    h = self.ce.mem_register(
                        value, refcount=len(children),
                        peers={msg["ranks"][p] for p in children})
                    d["wire"] = h.wire()
            self._send_to_children(tp, fwd, my_pos=my_pos)
            self._flush_if_unthreaded()

        self.ce.send_am(AM_TAG_GET_ACK, src, {"seq": msg["seq"]})
        pins.fire(PinsEvent.ACTIVATE_CB_END, None, (src, msg["seq"]))
        r = _spans.recorder
        if r is not None and msg.get("trace"):
            # the recv half of the activation hop: flow-keyed by the
            # SENDING rank + seq, matching the emitter's span
            r.record("comm.activate", msg["trace"], t0 or _now_ns(),
                     _now_ns(),
                     args={"flow": f"act:{src}:{msg['seq']}",
                           "flow_side": "recv",
                           "released": len(ready)})
        if ready:
            schedule_tasks(self._es, ready, 0)

    def _succ_rank(self, tc, locals_) -> int:
        if tc.affinity is None:
            return self.my_rank
        dc, key = tc.affinity(locals_)
        if not isinstance(key, tuple):
            key = (key,)
        return dc.rank_of(*key)
