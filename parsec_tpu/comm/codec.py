"""The wire codec: structured binary encoding with out-of-band buffers.

The DCN-tier transport used to pickle whole Python object graphs per frame
(``pickle.dumps((tag, src, payload))``), so every tile crossing ranks paid
serialize + copy + deserialize + copy — and every inbound frame ran the
pickle VM on network bytes.  This module replaces that with a compact
self-describing binary encoding in the msgpack spirit:

- :func:`encode` walks the payload once and returns ``(meta, segments)``:
  ``meta`` is a small bytes blob describing the structure, ``segments`` is
  a list of raw buffers (ndarray / big-bytes bodies) referenced **in
  order** by the meta.  Segments are never copied — the fabric hands them
  straight to ``socket.sendmsg`` (scatter-gather) so a tile's bytes go
  user-buffer → kernel with zero intermediate staging.
- :func:`decode` parses the meta and calls ``fill(view)`` for each
  segment, in order, with a **preallocated writable destination** (the
  final ndarray's flat byte view).  The socket receive loop passes a
  ``recv_into`` closure, so inbound payload bytes land socket → final
  buffer, also with zero intermediate staging.

Trust boundary (docs/COMM.md): the structured tags cover everything the
protocol layer ships (dicts/lists/tuples/scalars/str/bytes/ndarrays), and
decoding them can only ever materialize those types — no pickle VM, no
constructor calls.  Payload objects outside that set (user AMs carrying
arbitrary objects) fall back to an explicit ``T_PICKLE`` blob, decoded
through :class:`RestrictedUnpickler`, which refuses every global outside
an allowlist (numpy/jax reconstruction + this package + a few harmless
builtins) — ``os.system``-style gadget chains fail at find_class time.
Data frames (rendezvous GET payloads) never carry a pickle tag at all.
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Callable

import numpy as np

from ..core.params import params as _params

_params.register("comm_codec_pickle_fallback", True,
                 "allow control-frame payload nodes outside the structured "
                 "tag set to ride as restricted-pickle blobs (decoded "
                 "through the find_class allowlist); off makes an "
                 "unencodable payload a send-time TypeError")

# type tags ------------------------------------------------------------------
T_NONE = 0
T_TRUE = 1
T_FALSE = 2
T_INT = 3          # <q
T_FLOAT = 4        # <d
T_STR = 5          # <I len + utf8
T_BYTES = 6        # <I len + raw, inline in the meta (small)
T_LIST = 7         # <I count
T_TUPLE = 8        # <I count
T_DICT = 9         # <I count, then key/value pairs
T_NDARRAY = 10     # dtype + shape header; bytes ride as the next segment
T_JAX = 11         # same layout; decode lands a jax array (default device)
T_PICKLE = 12      # <I len + restricted-pickle blob (control frames only)
T_BIGBYTES = 13    # <Q len; bytes ride as the next segment

_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# bytes payloads at least this large ride out-of-band as segments instead
# of being memcpy'd into the meta blob
_BIG_BYTES = 512

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


def _is_jax_array(value: Any) -> bool:
    import sys
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(value, jax.Array)


def wire_dtype(dtype: Any) -> str:
    """The on-the-wire dtype name (round-trips through ``np.dtype``)."""
    return np.dtype(dtype).str


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def _encode_array_header(out: bytearray, tag: int, arr: np.ndarray) -> None:
    ds = wire_dtype(arr.dtype).encode()
    out.append(tag)
    out.append(len(ds))
    out += ds
    out.append(arr.ndim)
    for d in arr.shape:
        out += _I64.pack(d)
    out += _U64.pack(arr.nbytes)


def _encode(out: bytearray, segs: list, obj: Any) -> None:
    if obj is None:
        out.append(T_NONE)
    elif obj is True:
        out.append(T_TRUE)
    elif obj is False:
        out.append(T_FALSE)
    elif type(obj) is int:
        if _I64_MIN <= obj <= _I64_MAX:
            out.append(T_INT)
            out += _I64.pack(obj)
        else:
            _encode_fallback(out, obj)
    elif type(obj) is float:
        out.append(T_FLOAT)
        out += _F64.pack(obj)
    elif type(obj) is str:
        b = obj.encode()
        out.append(T_STR)
        out += _U32.pack(len(b))
        out += b
    elif type(obj) is bytes or type(obj) is bytearray:
        if len(obj) >= _BIG_BYTES:
            out.append(T_BIGBYTES)
            out += _U64.pack(len(obj))
            segs.append(obj)
        else:
            out.append(T_BYTES)
            out += _U32.pack(len(obj))
            out += obj
    elif type(obj) is list:
        out.append(T_LIST)
        out += _U32.pack(len(obj))
        for v in obj:
            _encode(out, segs, v)
    elif type(obj) is tuple:
        out.append(T_TUPLE)
        out += _U32.pack(len(obj))
        for v in obj:
            _encode(out, segs, v)
    elif type(obj) is dict:
        out.append(T_DICT)
        out += _U32.pack(len(obj))
        for k, v in obj.items():
            _encode(out, segs, k)
            _encode(out, segs, v)
    elif isinstance(obj, np.ndarray):
        if obj.dtype == object:
            _encode_fallback(out, obj)
            return
        if not obj.flags.c_contiguous:
            obj = np.ascontiguousarray(obj)
        _encode_array_header(out, T_NDARRAY, obj)
        if obj.nbytes:
            segs.append(obj)
    elif isinstance(obj, (np.bool_, np.integer, np.floating)):
        # numpy scalars (tile versions, counters) ride as their Python kin
        _encode(out, segs, obj.item())
    elif _is_jax_array(obj):
        host = np.ascontiguousarray(np.asarray(obj))
        _encode_array_header(out, T_JAX, host)
        if host.nbytes:
            segs.append(host)
    else:
        _encode_fallback(out, obj)


def _encode_fallback(out: bytearray, obj: Any) -> None:
    if not _params.get("comm_codec_pickle_fallback"):
        raise TypeError(
            f"payload node of type {type(obj).__name__} is outside the "
            f"structured wire tags and comm_codec_pickle_fallback is off")
    b = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    out.append(T_PICKLE)
    out += _U32.pack(len(b))
    out += b


def encode(obj: Any) -> tuple[bytearray, list]:
    """Encode ``obj`` → ``(meta, segments)``.  Segments are zero-copy
    references (the caller must transmit them before mutating sources —
    registered buffers are already stable snapshots)."""
    out = bytearray()
    segs: list = []
    _encode(out, segs, obj)
    return out, segs


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

class _Reader:
    __slots__ = ("mv", "pos")

    def __init__(self, buf: Any) -> None:
        self.mv = memoryview(buf)
        self.pos = 0

    def take(self, n: int) -> memoryview:
        p = self.pos
        self.pos = p + n
        return self.mv[p:p + n]

    def u8(self) -> int:
        p = self.pos
        self.pos = p + 1
        return self.mv[p]


def _decode_array(r: _Reader, fill: Callable, to_jax: bool) -> Any:
    dlen = r.u8()
    dtype = np.dtype(bytes(r.take(dlen)).decode())
    ndim = r.u8()
    shape = tuple(_I64.unpack(r.take(8))[0] for _ in range(ndim))
    nbytes = _U64.unpack(r.take(8))[0]
    arr = np.empty(shape, dtype)
    assert arr.nbytes == nbytes, (arr.nbytes, nbytes)
    if nbytes:
        # the zero-copy landing: fill() writes straight into the final
        # buffer (recv_into from the socket, or a memcpy from a segment)
        fill(memoryview(arr).cast("B"))
    if to_jax:
        import jax.numpy as jnp
        return jnp.asarray(arr)
    return arr


def _decode(r: _Reader, fill: Callable) -> Any:
    tag = r.u8()
    if tag == T_NONE:
        return None
    if tag == T_TRUE:
        return True
    if tag == T_FALSE:
        return False
    if tag == T_INT:
        return _I64.unpack(r.take(8))[0]
    if tag == T_FLOAT:
        return _F64.unpack(r.take(8))[0]
    if tag == T_STR:
        n = _U32.unpack(r.take(4))[0]
        return bytes(r.take(n)).decode()
    if tag == T_BYTES:
        n = _U32.unpack(r.take(4))[0]
        return bytes(r.take(n))
    if tag == T_LIST:
        n = _U32.unpack(r.take(4))[0]
        return [_decode(r, fill) for _ in range(n)]
    if tag == T_TUPLE:
        n = _U32.unpack(r.take(4))[0]
        return tuple(_decode(r, fill) for _ in range(n))
    if tag == T_DICT:
        n = _U32.unpack(r.take(4))[0]
        return {_decode(r, fill): _decode(r, fill) for _ in range(n)}
    if tag == T_NDARRAY:
        return _decode_array(r, fill, to_jax=False)
    if tag == T_JAX:
        return _decode_array(r, fill, to_jax=True)
    if tag == T_BIGBYTES:
        n = _U64.unpack(r.take(8))[0]
        buf = bytearray(n)
        fill(memoryview(buf))
        return bytes(buf)
    if tag == T_PICKLE:
        n = _U32.unpack(r.take(4))[0]
        return restricted_loads(bytes(r.take(n)))
    raise ValueError(f"unknown wire tag {tag}")


def decode(meta: Any, fill: Callable[[memoryview], None]) -> Any:
    """Decode a meta blob, pulling segment bytes through ``fill(view)``
    (called once per segment, in encode order, with the preallocated
    destination)."""
    return _decode(_Reader(meta), fill)


def decode_with_segments(meta: Any, segments: list) -> Any:
    """Convenience decode from in-memory segments (tests, loopback)."""
    it = iter(segments)

    def fill(view: memoryview) -> None:
        src = memoryview(next(it)).cast("B")
        view[:] = src
    return decode(meta, fill)


def roundtrip(obj: Any) -> Any:
    """encode → decode through memory (tests + the inproc codec check)."""
    meta, segs = encode(obj)
    return decode_with_segments(meta, segs)


# ---------------------------------------------------------------------------
# the restricted pickle seam (control frames only)
# ---------------------------------------------------------------------------

# (module, name) pairs outside the prefix allowlist that are still safe to
# reconstruct — extend deliberately, never wholesale
_SAFE_GLOBALS = {
    ("builtins", "complex"), ("builtins", "slice"), ("builtins", "range"),
    ("builtins", "set"), ("builtins", "frozenset"),
    ("builtins", "bytearray"),
    ("collections", "OrderedDict"), ("collections", "deque"),
}

# module prefixes whose globals may be reconstructed: the numeric stack
# (ndarray/dtype revival) and this package's own wire records.  The seam
# is defense-in-depth for same-trust-domain ranks, not a sandbox — see
# docs/COMM.md for the boundary statement.
_SAFE_PREFIXES = ("numpy", "jax", "jaxlib", "ml_dtypes", "parsec_tpu")


class RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):  # noqa: D102
        if (module, name) in _SAFE_GLOBALS or \
                module.split(".", 1)[0] in _SAFE_PREFIXES:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"wire pickle blob references {module}.{name}, which is "
            f"outside the control-frame allowlist (docs/COMM.md)")


def restricted_loads(data: bytes) -> Any:
    """``pickle.loads`` through the control-frame allowlist."""
    return RestrictedUnpickler(io.BytesIO(data)).load()
