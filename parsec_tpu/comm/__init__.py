"""Communication: comm-engine abstraction + remote-dep protocol.

Rebuild of the reference's communication stack (SURVEY §2.6, §3.4, §5.8):

- :mod:`engine` — the transport-neutral comm-engine vtable
  (``parsec_comm_engine.h:176-199``): active messages, registered memory,
  one-sided get/put, progress; with the in-process fabric backend (the
  rebuild's analog of oversubscribed-MPI CI runs) and the seam where an
  ICI/DCN transport slots in.
- :mod:`remote_dep` — the remote dependency-activation protocol
  (``remote_dep.c`` / ``remote_dep_mpi.c``): activation AMs carrying task
  coordinates, rendezvous GET for payloads, short-message inlining,
  binomial/chain/star propagation trees, per-peer coalescing, and the
  termination-detection pending-action discipline.
- :mod:`multirank` — N-rank harness: one runtime context per rank over a
  shared fabric (the test-facing analog of ``mpiexec -np N``).
- :mod:`socket_fabric` / :mod:`multiproc` — the multi-PROCESS tier: ranks
  as separate interpreters over TCP (``run_multiproc``, the true mpiexec
  analog; set ``PARSEC_TPU_HOSTS`` for multi-host), with seq/replay/ack
  delivery guarantees over breakable connections and the zero-copy binary
  wire framing (scatter-gather sends, recv_into landings — docs/COMM.md).
- :mod:`codec` — the structured wire codec + restricted-pickle control
  seam: payload structure as a compact meta blob, tile bytes as
  out-of-band raw segments, never the bare pickle VM on network bytes.
- :mod:`device_socket` — the deployable DCN tier:
  ``run_multiproc(transport="device")`` binds one JAX device per rank,
  registered payloads live device-resident, GETs land straight on the
  consumer's device, and ``jax.distributed`` bootstraps real pods.
"""

from . import codec
from .engine import (AM_TAG_ACTIVATE, AM_TAG_GET_ACK, AM_TAG_TERMDET,
                     CommEngine, InprocFabric, MemHandle)
from .remote_dep import (RemoteDepEngine, RemoteDeps, TREE_KINDS,
                         tree_children, tree_parent)
from .collectives import (bcast_taskpool, reduce_taskpool,
                          register_reduce_op, reduce_op)
from .multirank import run_multirank
from .multiproc import run_multiproc
from .device_socket import DeviceSocketCommEngine
from .termdet_fourcounter import FourCounterTermDet  # registers the component

__all__ = [
    "CommEngine", "InprocFabric", "MemHandle", "RemoteDepEngine",
    "RemoteDeps", "FourCounterTermDet", "run_multirank", "run_multiproc",
    "DeviceSocketCommEngine", "AM_TAG_ACTIVATE",
    "AM_TAG_GET_ACK", "AM_TAG_TERMDET", "codec",
    "TREE_KINDS", "tree_children", "tree_parent",
    "bcast_taskpool", "reduce_taskpool", "register_reduce_op", "reduce_op",
]
