"""Socket transport: the multi-PROCESS comm backend (the DCN tier).

SURVEY §5.8 maps the reference's transport tiers onto TPU pods as
ICI (device-to-device, :mod:`device_fabric`) for in-pod payloads and
DCN/host networking across pods.  This module is the DCN tier: each rank
is its own OS process, active messages and rendezvous payloads move over
TCP, and the entire protocol stack above the engine vtable — remote-dep
activation, propagation trees, coalescing, termdet waves, DTD pushes —
runs unchanged (``RemoteDepEngine`` never learns which fabric it rides).

Wire format: length-prefixed pickles of ``("d", seq, body)`` data frames
(``body`` = the pickled ``(tag, src, payload)``, serialized outside the
per-peer send lock) and ``("a", src, upto)`` cumulative acks.  Topology: rank *i*
listens on ``base_port + i``; outgoing connections are made lazily with
connect-retry (peers boot in any order).  The host list defaults to
localhost (the oversubscribed test form — real multi-host runs set
``PARSEC_TPU_HOSTS=h0,h1,...``).

Fault model: TCP gives in-order reliable delivery *per connection*, but a
broken connection loses whatever was buffered in flight.  Each peer channel
therefore carries a monotonically increasing ``seq``; the sender keeps every
unacked frame in a bounded replay window and, when a send fails, reconnects
and replays the window; the receiver acks cumulatively every few frames and
drops duplicates by sequence — so a connection reset anywhere between two
ranks is invisible above the fabric (exactly-once, in-order per channel).

Use :func:`parsec_tpu.comm.multiproc.run_multiproc` to launch N subprocess
ranks and collect their results — the ``mpiexec -np N`` analog.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any

from ..core.params import params as _params
from .engine import InprocCommEngine

_params.register("comm_socket_base_port", 39100,
                 "first TCP port of the socket fabric (rank i listens on "
                 "base+i)")
_params.register("comm_socket_ack_every", 16,
                 "receiver sends a cumulative ack after this many frames "
                 "(bounds the sender's replay window)")
_params.register("comm_socket_replay_window", 4096,
                 "max unacked frames retained per peer for reconnect "
                 "replay; exceeding it is a visible error (a peer that "
                 "stopped acking)")
_params.register("comm_socket_fault_p", 0.0,
                 "fault injection: probability per outgoing frame of "
                 "breaking the connection first (tests the "
                 "reconnect-and-replay path; 0 disables)")
_params.register("comm_socket_fault_seed", 0,
                 "seed for the fault-injection RNG (per-rank offset added)")

_LEN = struct.Struct("<Q")


def _hosts(nranks: int) -> list[str]:
    spec = os.environ.get("PARSEC_TPU_HOSTS", "")
    hosts = [h.strip() for h in spec.split(",") if h.strip()]
    if not hosts:
        hosts = ["127.0.0.1"]
    return [hosts[r % len(hosts)] for r in range(nranks)]


def _frame(obj: Any) -> bytes:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _LEN.pack(len(data)) + data


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class SocketFabric:
    """One process's endpoint of the TCP mesh (quacks like InprocFabric
    for the local rank: deliver / drain / pending)."""

    def __init__(self, nranks: int, rank: int,
                 base_port: int | None = None) -> None:
        self.nranks = nranks
        self.rank = rank
        self.base_port = base_port if base_port is not None else \
            _params.get("comm_socket_base_port")
        self.hosts = _hosts(nranks)
        self._inbox: deque = deque()
        self._ilock = threading.Lock()
        # dst -> [sock|None, send-lock, next_seq, unacked deque[(seq, bytes)]]
        self._peers: dict[int, list] = {}
        self._plock = threading.Lock()
        # receiver-side channel state (guarded by _ilock): highest seq seen
        # per src (duplicate suppression) and frames since the last ack
        self._seen: dict[int, int] = {}
        self._unacked_in: dict[int, int] = {}
        self.replays = 0          # reconnect-and-replay events (observable)
        self.dup_frames = 0       # duplicate frames suppressed
        self.bytes_sent = 0       # total framed bytes (traffic accounting)
        # fault injection (tests): break the connection before some sends
        fault_p = float(_params.get("comm_socket_fault_p"))
        self._fault_p = fault_p
        if fault_p > 0.0:
            import random
            self._fault_rng = random.Random(
                _params.get("comm_socket_fault_seed") + rank)
        else:
            self._fault_rng = None
        # engine hook: invoked with a rank when it stays unreachable past
        # the reconnect budget (SocketCommEngine points this at its
        # registered-buffer GC, CommEngine.on_peer_failed)
        self.on_peer_dead = None
        self._accepted: list[socket.socket] = []   # inbound conns, for close
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", self.base_port + rank))
        self._listener.listen(nranks)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_main, daemon=True,
            name=f"parsec-sock-accept-r{rank}")
        self._accept_thread.start()

    # ------------------------------------------------------------ receive
    def _accept_main(self) -> None:
        while not self._stop.is_set():
            try:
                self._listener.settimeout(0.2)
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._plock:
                self._accepted.append(conn)
            if self._stop.is_set():
                # raced with close(): it may have cleared _accepted before
                # our append — clean up here instead of leaking the conn
                # (separate try blocks: shutdown of a dead peer raises
                # ENOTCONN and must not skip the close)
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(target=self._recv_main, args=(conn,),
                             daemon=True).start()

    def _recv_main(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        ack_every = _params.get("comm_socket_ack_every")
        while not self._stop.is_set():
            try:
                head = _recv_exact(conn, _LEN.size)
                if head is None:
                    return
                body = _recv_exact(conn, _LEN.unpack(head)[0])
                if body is None:
                    return
                frame = pickle.loads(body)
            except OSError:
                return
            except Exception as e:
                # a corrupt/undecodable frame kills only THIS connection —
                # visibly.  The peer's replay window re-sends everything it
                # had in flight on its next send; the seq dedup below keeps
                # delivery exactly-once across the reset.
                from ..core.output import warning
                warning(f"socket fabric rank {self.rank}: dropping "
                        f"connection on undecodable frame: {e!r}")
                try:
                    conn.close()
                except OSError:
                    pass
                return
            if frame[0] == "a":                  # cumulative ack
                _, src, upto = frame
                self._prune_unacked(src, upto)
                continue
            _, seq, body = frame
            tag, src, payload = pickle.loads(body)
            ack_now = None
            with self._ilock:
                if seq <= self._seen.get(src, 0):
                    self.dup_frames += 1         # replay overlap: suppress
                else:
                    self._seen[src] = seq
                    self._inbox.append((tag, src, payload))
                n = self._unacked_in.get(src, 0) + 1
                if n >= ack_every:
                    self._unacked_in[src] = 0
                    ack_now = self._seen[src]
                else:
                    self._unacked_in[src] = n
            if ack_now is not None:
                self._send_ack(src, ack_now)

    def _prune_unacked(self, src: int, upto: int) -> None:
        with self._plock:
            ent = self._peers.get(src)
        if ent is None:
            return
        with ent[1]:
            q = ent[3]
            while q and q[0][0] <= upto:
                q.popleft()

    def _send_ack(self, src: int, upto: int) -> None:
        """Best-effort cumulative ack (idempotent: never replayed; a lost
        ack just leaves the peer's window larger until the next one).
        Runs on a receive thread, so a missing reverse connection gets only
        a SHORT connect budget — stalling reception behind a 30s boot retry
        would freeze frames already queued on this connection.  A failed
        send DROPS the socket (the next ack reconnects) and never declares
        the peer dead — a receive-only rank's ack channel would otherwise
        stay wedged after one reset and starve the sender's window."""
        with self._plock:
            ent = self._peers.get(src)
            if ent is None:
                ent = self._peers[src] = [None, threading.Lock(), 0, deque()]
        with ent[1]:
            try:
                if ent[0] is None:
                    ent[0] = self._connect(src, retry_s=2.0,
                                           report_dead=False)
                ent[0].sendall(_frame(("a", self.rank, upto)))
            except OSError:
                if ent[0] is not None:
                    try:
                        ent[0].close()
                    except OSError:
                        pass
                    ent[0] = None

    # --------------------------------------------------------------- send
    def _connect(self, dst: int, retry_s: float = 30.0,
                 report_dead: bool = True) -> socket.socket:
        """Connect to ``dst``, retrying refusals for up to ``retry_s`` (30s
        default covers peers still booting; reconnect paths pass a short
        budget — a peer dead mid-run should fail fast, not hang callers for
        the boot window).  Bails immediately on fabric teardown.
        ``report_dead=False`` suppresses the peer-death notification —
        best-effort paths (acks) must not declare a live peer dead off a
        short transient budget."""
        deadline = time.monotonic() + retry_s
        while True:
            if self._stop.is_set():
                raise OSError("fabric is shutting down")
            try:
                s = socket.create_connection(
                    (self.hosts[dst], self.base_port + dst), timeout=2.0)
                break
            except OSError:
                if time.monotonic() > deadline:
                    if report_dead:
                        self._peer_dead(dst)
                    raise
                time.sleep(0.05)   # peer still booting
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _peer_dead(self, dst: int) -> None:
        """A peer is unreachable past its retry budget: tell the engine so
        it can release resources pinned for that rank (registered-buffer
        shares via ``CommEngine.on_peer_failed``)."""
        cb = self.on_peer_dead
        if cb is not None:
            try:
                cb(dst)
            except Exception:       # a GC hook must never mask the OSError
                pass

    def deliver(self, dst: int, tag: int, src: int, payload: Any) -> None:
        if dst == self.rank:
            with self._ilock:
                self._inbox.append((tag, src, payload))
            return
        # the expensive serialization (payload object graph) runs OUTSIDE
        # the send lock; only the tiny seq-stamped envelope (a bytes
        # memcpy) is built inside it
        body = pickle.dumps((tag, src, payload),
                            protocol=pickle.HIGHEST_PROTOCOL)
        with self._plock:
            ent = self._peers.get(dst)
            if ent is None:
                ent = self._peers[dst] = [None, threading.Lock(), 0, deque()]
        with ent[1]:     # frames must not interleave on one connection
            if len(ent[3]) >= _params.get("comm_socket_replay_window"):
                raise RuntimeError(
                    f"rank {self.rank}: replay window to rank {dst} full "
                    f"({len(ent[3])} unacked frames) — peer stopped acking")
            ent[2] += 1
            seq = ent[2]
            data = _frame(("d", seq, body))
            # bytes_sent is shared across peers; concurrent senders hold
            # different per-peer locks, so the read-modify-write needs the
            # peer-table lock to not lose increments
            with self._plock:
                self.bytes_sent += len(data)
            ent[3].append((seq, data))
            if ent[0] is None:
                ent[0] = self._connect(dst)
            if (self._fault_rng is not None
                    and self._fault_rng.random() < self._fault_p):
                # injected fault: hard-break the live connection so this
                # send fails and exercises reconnect-and-replay
                try:
                    ent[0].shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            try:
                ent[0].sendall(data)
            except OSError:
                self._reconnect_and_replay(dst, ent)

    def _reconnect_and_replay(self, dst: int, ent: list) -> None:
        """A broken connection loses whatever TCP had buffered: reconnect
        and resend the whole unacked window in order (caller holds the
        send lock).  The receiver's seq dedup drops the overlap."""
        try:
            if ent[0] is not None:
                ent[0].close()
        except OSError:
            pass
        ent[0] = None
        self.replays += 1
        ent[0] = self._connect(dst, retry_s=5.0)
        for _seq, data in list(ent[3]):
            ent[0].sendall(data)     # a second failure here is fatal: raise

    # ----------------------------------------------------- drain (local)
    def drain(self, rank: int, limit: int = 64) -> list[tuple]:
        assert rank == self.rank
        out = []
        with self._ilock:
            while self._inbox and len(out) < limit:
                out.append(self._inbox.popleft())
        return out

    def pending(self, rank: int) -> int:
        assert rank == self.rank
        with self._ilock:
            return len(self._inbox)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._plock:
            for ent in self._peers.values():
                if ent[0] is not None:
                    try:
                        ent[0].shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        ent[0].close()
                    except OSError:
                        pass
            self._peers.clear()
            # shutdown() (not just close()) unblocks recv threads parked in
            # recv(2) — close alone only drops the fd reference while the
            # syscall keeps blocking — so _recv_main exits and no
            # thread/fd accumulates across fabric create/teardown cycles
            for conn in self._accepted:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            self._accepted.clear()


class SocketCommEngine(InprocCommEngine):
    """The engine vtable over :class:`SocketFabric`.

    :class:`SocketFabric` exposes the same deliver/drain/pending surface
    the in-process fabric does, so the whole AM + rendezvous-GET + barrier
    protocol is inherited verbatim — the engine cannot tell whether its
    bytes cross a deque or a TCP connection, which is exactly the vtable
    discipline the reference's comm engines follow
    (``parsec_comm_engine.h:176-199``)."""

    def __init__(self, fabric: SocketFabric) -> None:
        super().__init__(fabric, fabric.rank)
        # a rank unreachable past the reconnect budget releases its
        # registered-buffer shares (the peer-death GC)
        fabric.on_peer_dead = self.on_peer_failed

    def fini(self) -> None:
        super().fini()          # force-drop leftover registrations first
        self.fabric.close()
