"""Socket transport: the multi-PROCESS comm backend (the DCN tier).

SURVEY §5.8 maps the reference's transport tiers onto TPU pods as
ICI (device-to-device, :mod:`device_fabric`) for in-pod payloads and
DCN/host networking across pods.  This module is the DCN tier: each rank
is its own OS process, active messages and rendezvous payloads move over
TCP, and the entire protocol stack above the engine vtable — remote-dep
activation, propagation trees, coalescing, termdet waves, DTD pushes —
runs unchanged (``RemoteDepEngine`` never learns which fabric it rides).

Wire format: length-prefixed pickles of ``(tag, src, payload)`` frames.
Topology: rank *i* listens on ``base_port + i``; outgoing connections are
made lazily with connect-retry (peers boot in any order).  The host list
defaults to localhost (the oversubscribed test form — real multi-host runs
set ``PARSEC_TPU_HOSTS=h0,h1,...``).

Use :func:`parsec_tpu.comm.multiproc.run_multiproc` to launch N subprocess
ranks and collect their results — the ``mpiexec -np N`` analog.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any

from ..core.params import params as _params
from .engine import InprocCommEngine

_params.register("comm_socket_base_port", 39100,
                 "first TCP port of the socket fabric (rank i listens on "
                 "base+i)")

_LEN = struct.Struct("<Q")


def _hosts(nranks: int) -> list[str]:
    spec = os.environ.get("PARSEC_TPU_HOSTS", "")
    hosts = [h.strip() for h in spec.split(",") if h.strip()]
    if not hosts:
        hosts = ["127.0.0.1"]
    return [hosts[r % len(hosts)] for r in range(nranks)]


def _frame(obj: Any) -> bytes:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _LEN.pack(len(data)) + data


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class SocketFabric:
    """One process's endpoint of the TCP mesh (quacks like InprocFabric
    for the local rank: deliver / drain / pending)."""

    def __init__(self, nranks: int, rank: int,
                 base_port: int | None = None) -> None:
        self.nranks = nranks
        self.rank = rank
        self.base_port = base_port if base_port is not None else \
            _params.get("comm_socket_base_port")
        self.hosts = _hosts(nranks)
        self._inbox: deque = deque()
        self._ilock = threading.Lock()
        self._peers: dict[int, list] = {}   # dst -> [sock|None, send-lock]
        self._plock = threading.Lock()
        self._accepted: list[socket.socket] = []   # inbound conns, for close
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", self.base_port + rank))
        self._listener.listen(nranks)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_main, daemon=True,
            name=f"parsec-sock-accept-r{rank}")
        self._accept_thread.start()

    # ------------------------------------------------------------ receive
    def _accept_main(self) -> None:
        while not self._stop.is_set():
            try:
                self._listener.settimeout(0.2)
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._plock:
                self._accepted.append(conn)
            if self._stop.is_set():
                # raced with close(): it may have cleared _accepted before
                # our append — clean up here instead of leaking the conn
                # (separate try blocks: shutdown of a dead peer raises
                # ENOTCONN and must not skip the close)
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(target=self._recv_main, args=(conn,),
                             daemon=True).start()

    def _recv_main(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while not self._stop.is_set():
            try:
                head = _recv_exact(conn, _LEN.size)
                if head is None:
                    return
                body = _recv_exact(conn, _LEN.unpack(head)[0])
                if body is None:
                    return
                frame = pickle.loads(body)
            except OSError:
                return
            except Exception as e:
                # a corrupt/unimportable payload must be VISIBLE, not a
                # silently dead receiver thread with a stalled connection
                from ..core.output import warning
                warning(f"socket fabric rank {self.rank}: dropping "
                        f"connection on undecodable frame: {e!r}")
                try:
                    conn.close()
                except OSError:
                    pass
                return
            with self._ilock:
                self._inbox.append(frame)

    # --------------------------------------------------------------- send
    def _peer(self, dst: int) -> tuple[socket.socket | None, threading.Lock]:
        """The (socket, send-lock) pair for ``dst``.  The global lock only
        installs the per-destination slot; the (up to 30s) connect-retry
        runs under the slot's own lock, so a slow-booting peer never
        stalls sends to peers that are already connected."""
        with self._plock:
            ent = self._peers.get(dst)
            if ent is None:
                ent = self._peers[dst] = [None, threading.Lock()]
        with ent[1]:
            if ent[0] is None:
                deadline = time.monotonic() + 30.0
                while True:
                    try:
                        s = socket.create_connection(
                            (self.hosts[dst], self.base_port + dst),
                            timeout=2.0)
                        break
                    except OSError:
                        if time.monotonic() > deadline:
                            raise
                        time.sleep(0.05)   # peer still booting
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                ent[0] = s
        return ent[0], ent[1]

    def deliver(self, dst: int, tag: int, src: int, payload: Any) -> None:
        if dst == self.rank:
            with self._ilock:
                self._inbox.append((tag, src, payload))
            return
        data = _frame((tag, src, payload))   # pickle OUTSIDE the send lock
        s, lock = self._peer(dst)
        with lock:    # frames must not interleave on one connection
            s.sendall(data)

    # ----------------------------------------------------- drain (local)
    def drain(self, rank: int, limit: int = 64) -> list[tuple]:
        assert rank == self.rank
        out = []
        with self._ilock:
            while self._inbox and len(out) < limit:
                out.append(self._inbox.popleft())
        return out

    def pending(self, rank: int) -> int:
        assert rank == self.rank
        with self._ilock:
            return len(self._inbox)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._plock:
            for ent in self._peers.values():
                if ent[0] is not None:
                    try:
                        ent[0].shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        ent[0].close()
                    except OSError:
                        pass
            self._peers.clear()
            # shutdown() (not just close()) unblocks recv threads parked in
            # recv(2) — close alone only drops the fd reference while the
            # syscall keeps blocking — so _recv_main exits and no
            # thread/fd accumulates across fabric create/teardown cycles
            for conn in self._accepted:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            self._accepted.clear()


class SocketCommEngine(InprocCommEngine):
    """The engine vtable over :class:`SocketFabric`.

    :class:`SocketFabric` exposes the same deliver/drain/pending surface
    the in-process fabric does, so the whole AM + rendezvous-GET + barrier
    protocol is inherited verbatim — the engine cannot tell whether its
    bytes cross a deque or a TCP connection, which is exactly the vtable
    discipline the reference's comm engines follow
    (``parsec_comm_engine.h:176-199``)."""

    def __init__(self, fabric: SocketFabric) -> None:
        super().__init__(fabric, fabric.rank)

    def fini(self) -> None:
        self.fabric.close()
