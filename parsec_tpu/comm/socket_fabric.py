"""Socket transport: the multi-PROCESS comm backend (the DCN tier).

SURVEY §5.8 maps the reference's transport tiers onto TPU pods as
ICI (device-to-device, :mod:`device_fabric`) for in-pod payloads and
DCN/host networking across pods.  This module is the DCN tier: each rank
is its own OS process, active messages and rendezvous payloads move over
TCP, and the entire protocol stack above the engine vtable — remote-dep
activation, propagation trees, coalescing, termdet waves, DTD pushes —
runs unchanged (``RemoteDepEngine`` never learns which fabric it rides).

Wire format (``comm_wire_binary``, the default): every frame is a fixed
40-byte struct header ``<BBHIQQQQ`` = (kind, flags, tag, src, seq, u0, u1,
u2) followed by a kind-specific body:

- ``CTRL`` — an active message.  u0 = meta length, u1 = total raw-segment
  bytes, u2 = the 8-byte **trace context** of the request the message
  belongs to (0 = untraced; ``prof/spans.py`` — the receive thread
  span-records traced frames, so a request's wire hops appear in its
  trace).  Body = codec meta blob + raw buffer segments (ndarray bodies),
  sent with ``socket.sendmsg`` scatter-gather straight from the payload's
  own buffers and received with ``recv_into`` straight into freshly
  allocated final buffers (:mod:`parsec_tpu.comm.codec`) — no pickling of
  data, no staging copies on either side.
- ``ACK`` — cumulative receive ack, header only (seq = acked-upto).
- ``DATA`` — one rendezvous GET fragment.  u0 = get id, u1 = byte offset,
  u2 = fragment length; flag bit 0 marks the first fragment (body is then
  prefixed by the codec-encoded shape/dtype meta).  The receive thread
  asks the engine for the fragment's **final destination slice**
  (:meth:`~parsec_tpu.comm.engine.InprocCommEngine.landing_view`) and
  ``recv_into``\\ s it directly — socket → destination tile, zero copies.

``comm_wire_binary=False`` falls back to the legacy length-prefixed-pickle
framing (the measured baseline of ``microbench.bench_comm``); both ends of
a fabric must agree.  Topology: rank *i*
listens on ``base_port + i``; outgoing connections are made lazily with
connect-retry (peers boot in any order).  The host list defaults to
localhost (the oversubscribed test form — real multi-host runs set
``PARSEC_TPU_HOSTS=h0,h1,...``).

Fault model: TCP gives in-order reliable delivery *per connection*, but a
broken connection loses whatever was buffered in flight.  Each peer channel
therefore carries a monotonically increasing ``seq``; the sender keeps every
unacked frame in a bounded replay window and, when a send fails, reconnects
and replays the window; the receiver acks cumulatively every few frames and
drops duplicates by sequence — so a connection reset anywhere between two
ranks is invisible above the fabric (exactly-once, in-order per channel).

Use :func:`parsec_tpu.comm.multiproc.run_multiproc` to launch N subprocess
ranks and collect their results — the ``mpiexec -np N`` analog.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any

from ..core.params import params as _params
from ..data.arena import wire_pool
from ..prof import spans as _spans
from . import codec
from .engine import AM_TAG_GET_FRAG, InprocCommEngine

_params.register("comm_wire_binary", True,
                 "binary wire framing on the socket fabric: struct headers "
                 "+ scatter-gather raw segments (sendmsg/recv_into); off "
                 "reverts to length-prefixed pickle frames (both ends of a "
                 "fabric must agree)")
_params.register("comm_socket_base_port", 39100,
                 "first TCP port of the socket fabric (rank i listens on "
                 "base+i)")
_params.register("comm_socket_ack_every", 16,
                 "receiver sends a cumulative ack after this many frames "
                 "(bounds the sender's replay window)")
_params.register("comm_socket_replay_window", 4096,
                 "max unacked frames retained per peer for reconnect "
                 "replay; exceeding it is a visible error (a peer that "
                 "stopped acking)")
_params.register("comm_socket_fault_p", 0.0,
                 "fault injection: probability per outgoing frame of "
                 "breaking the connection first (tests the "
                 "reconnect-and-replay path; 0 disables)")
_params.register("comm_socket_fault_seed", 0,
                 "seed for the fault-injection RNG (per-rank offset added)")
# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# receive-side channel state mutates only under _ilock (shared by every
# per-connection receive thread), sender-side peer table and cross-peer
# traffic ledgers only under _plock; per-peer connection entries (ent[0..3])
# are guarded by the entry's own send lock (ent[1]) — anonymous, so outside
# the lint's reach (kept hierarchical by construction).  No site nests the
# two named locks; the declared order documents the intended direction.
_LOCK_PROTECTED = {
    "SocketFabric._inbox": "_ilock",
    "SocketFabric._seen": "_ilock",
    "SocketFabric._unacked_in": "_ilock",
    "SocketFabric.peer_rx": "_ilock",
    "SocketFabric.bytes_recv": "_ilock",
    "SocketFabric.dup_frames": "_ilock",
    "SocketFabric._peers": "_plock",
    "SocketFabric._accepted": "_plock",
    "SocketFabric.bytes_sent": "_plock",
    "SocketFabric.peer_tx": "_plock",
}
_LOCK_ORDER = ("_plock", "_ilock")

_params.register("comm_socket_buf_bytes", 1 << 22,
                 "SO_SNDBUF/SO_RCVBUF hint per connection (0 = OS default); "
                 "large GET fragments stream without stalling on the "
                 "default ~64KiB kernel buffers")


def _tune_socket(s: socket.socket) -> None:
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    buf = int(_params.get("comm_socket_buf_bytes"))
    if buf > 0:
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, buf)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, buf)
        except OSError:
            pass        # a capped kernel clamps silently anyway

_LEN = struct.Struct("<Q")

# binary frame header: kind, flags, tag, src, seq, u0, u1, u2 (see module
# docstring for the per-kind field meanings)
_HDR = struct.Struct("<BBHIQQQQ")
K_CTRL = 1
K_ACK = 2
K_DATA = 3
F_FIRST = 1       # DATA: first fragment (body carries the shape/dtype meta)
F_LAST = 2        # DATA: last fragment of its GET
_U32 = struct.Struct("<I")


def _hosts(nranks: int) -> list[str]:
    spec = os.environ.get("PARSEC_TPU_HOSTS", "")
    hosts = [h.strip() for h in spec.split(",") if h.strip()]
    if not hosts:
        hosts = ["127.0.0.1"]
    return [hosts[r % len(hosts)] for r in range(nranks)]


def _frame(obj: Any) -> bytes:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _LEN.pack(len(data)) + data


def _recv_exact_into(sock: socket.socket, mv: memoryview) -> bool:
    """Fill ``mv`` from the socket; False on EOF.  ``recv_into`` lands the
    bytes in place — the receive path's one and only copy is kernel→buffer."""
    while mv.nbytes:
        n = sock.recv_into(mv)
        if n == 0:
            return False
        mv = mv[n:]
    return True


def _recv_exact(sock: socket.socket, n: int) -> bytearray | None:
    """Exact-length receive into ONE preallocated buffer (no per-chunk
    ``bytearray +=`` growth copies)."""
    buf = bytearray(n)
    if not _recv_exact_into(sock, memoryview(buf)):
        return None
    return buf


def _drain(sock: socket.socket, n: int) -> bool:
    """Consume and discard ``n`` body bytes (duplicate/stale frames whose
    payload has nowhere to land) through a pooled scratch buffer."""
    mv = wire_pool.acquire(min(n, 1 << 16))
    try:
        while n:
            take = mv[:min(n, mv.nbytes)]
            if not _recv_exact_into(sock, take):
                return False
            n -= take.nbytes
        return True
    finally:
        wire_pool.release(mv)


# Linux caps one sendmsg at UIO_MAXIOV iovecs; stay safely under it (a
# coalesced flush of >1000 inline-payload activations can exceed it)
_IOV_MAX = 512


def _sendmsg_all(sock: socket.socket, bufs: list) -> None:
    """``sendmsg`` the scatter-gather list fully, resuming after short
    writes and chunking to the iovec limit (the vectored-send analog of
    ``sendall``)."""
    views = []
    for b in bufs:
        v = memoryview(b).cast("B")
        if v.nbytes:
            views.append(v)
    while views:
        chunk = views[:_IOV_MAX]
        chunk_total = sum(v.nbytes for v in chunk)
        n = sock.sendmsg(chunk)
        if n >= chunk_total:
            del views[:len(chunk)]
            continue
        while n:
            if n >= views[0].nbytes:
                n -= views[0].nbytes
                views.pop(0)
            else:
                views[0] = views[0][n:]
                n = 0


class SocketFabric:
    """One process's endpoint of the TCP mesh (quacks like InprocFabric
    for the local rank: deliver / drain / pending)."""

    def __init__(self, nranks: int, rank: int,
                 base_port: int | None = None) -> None:
        self.nranks = nranks
        self.rank = rank
        self.base_port = base_port if base_port is not None else \
            _params.get("comm_socket_base_port")
        self.hosts = _hosts(nranks)
        self._inbox: deque = deque()
        self._ilock = threading.Lock()
        # dst -> [sock|None, send-lock, next_seq, unacked deque[(seq, bytes)]]
        self._peers: dict[int, list] = {}
        self._plock = threading.Lock()
        # receiver-side channel state (guarded by _ilock): highest seq seen
        # per src (duplicate suppression) and frames since the last ack
        self._seen: dict[int, int] = {}
        self._unacked_in: dict[int, int] = {}
        self.replays = 0          # reconnect-and-replay events (observable)
        self.dup_frames = 0       # duplicate frames suppressed
        self.bytes_sent = 0       # total framed bytes (traffic accounting)
        self.bytes_recv = 0       # total framed bytes received (gauge twin)
        self.binary = bool(_params.get("comm_wire_binary"))
        # per-peer traffic ledgers: dst -> [bytes, frames, frags] (tx under
        # _plock, rx under _ilock) — the per-peer gauges of docs/COMM.md
        self.peer_tx: dict[int, list] = {}
        self.peer_rx: dict[int, list] = {}
        # engine hook: the socket receive thread lands DATA-frame bytes
        # through this (InprocCommEngine.landing_view); None until an
        # engine attaches — frames arriving earlier drain to scratch
        self.landing_view = None
        # fault injection (tests): break the connection before some sends
        fault_p = float(_params.get("comm_socket_fault_p"))
        self._fault_p = fault_p
        if fault_p > 0.0:
            import random
            self._fault_rng = random.Random(
                _params.get("comm_socket_fault_seed") + rank)
        else:
            self._fault_rng = None
        # engine hook: invoked with a rank when it stays unreachable past
        # the reconnect budget (SocketCommEngine points this at its
        # registered-buffer GC, CommEngine.on_peer_failed)
        self.on_peer_dead = None
        self._accepted: list[socket.socket] = []   # inbound conns, for close
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", self.base_port + rank))
        self._listener.listen(nranks)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_main, daemon=True,
            name=f"parsec-sock-accept-r{rank}")
        self._accept_thread.start()

    # ------------------------------------------------------------ receive
    def _accept_main(self) -> None:
        while not self._stop.is_set():
            try:
                self._listener.settimeout(0.2)
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._plock:
                self._accepted.append(conn)
            if self._stop.is_set():
                # raced with close(): it may have cleared _accepted before
                # our append — clean up here instead of leaking the conn
                # (separate try blocks: shutdown of a dead peer raises
                # ENOTCONN and must not skip the close)
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(target=self._recv_main, args=(conn,),
                             daemon=True).start()

    def _recv_main(self, conn: socket.socket) -> None:
        _tune_socket(conn)
        if self.binary:
            self._recv_main_binary(conn)
            return
        ack_every = _params.get("comm_socket_ack_every")
        while not self._stop.is_set():
            try:
                head = _recv_exact(conn, _LEN.size)
                if head is None:
                    return
                body = _recv_exact(conn, _LEN.unpack(head)[0])
                if body is None:
                    return
                # network bytes never hit the bare pickle VM: the legacy
                # framing decodes through the control-frame allowlist too
                frame = codec.restricted_loads(bytes(body))
            except OSError:
                return
            except Exception as e:
                # a corrupt/undecodable frame kills only THIS connection —
                # visibly.  The peer's replay window re-sends everything it
                # had in flight on its next send; the seq dedup below keeps
                # delivery exactly-once across the reset.
                from ..core.output import warning
                warning(f"socket fabric rank {self.rank}: dropping "
                        f"connection on undecodable frame: {e!r}")
                try:
                    conn.close()
                except OSError:
                    pass
                return
            if frame[0] == "a":                  # cumulative ack
                _, src, upto = frame
                self._prune_unacked(src, upto)
                continue
            _, seq, body = frame
            tag, src, payload = codec.restricted_loads(bytes(body))
            ack_now = None
            with self._ilock:
                if seq <= self._seen.get(src, 0):
                    self.dup_frames += 1         # replay overlap: suppress
                else:
                    self._seen[src] = seq
                    self._inbox.append((tag, src, payload))
                n = self._unacked_in.get(src, 0) + 1
                if n >= ack_every:
                    self._unacked_in[src] = 0
                    ack_now = self._seen[src]
                else:
                    self._unacked_in[src] = n
            if ack_now is not None:
                self._send_ack(src, ack_now)

    # ------------------------------------------------- binary receive loop
    def _recv_main_binary(self, conn: socket.socket) -> None:
        ack_every = _params.get("comm_socket_ack_every")
        hdr = bytearray(_HDR.size)
        while not self._stop.is_set():
            try:
                if not _recv_exact_into(conn, memoryview(hdr)):
                    return
                kind, flags, tag, src, seq, u0, u1, u2 = _HDR.unpack(hdr)
                if kind == K_ACK:
                    self._prune_unacked(src, seq)
                    continue
                if kind == K_CTRL:
                    self._recv_ctrl(conn, tag, src, seq, u0, u1, ack_every,
                                    trace_id=u2)
                elif kind == K_DATA:
                    self._recv_data(conn, flags, src, seq, u0, u1, u2,
                                    ack_every)
                else:
                    raise ValueError(f"unknown wire frame kind {kind}")
            except OSError:
                return
            except Exception as e:
                # a corrupt/undecodable frame kills only THIS connection —
                # visibly; the peer's replay window recovers the traffic
                from ..core.output import warning
                warning(f"socket fabric rank {self.rank}: dropping "
                        f"connection on undecodable frame: {e!r}")
                try:
                    conn.close()
                except OSError:
                    pass
                return

    def _rx_account(self, src: int, nbytes: int, frag: bool) -> None:
        """Caller holds ``_ilock``."""
        self.bytes_recv += nbytes
        rx = self.peer_rx.get(src)
        if rx is None:
            rx = self.peer_rx[src] = [0, 0, 0]
        rx[0] += nbytes
        rx[1] += 1
        if frag:
            rx[2] += 1

    def _recv_ctrl(self, conn: socket.socket, tag: int, src: int, seq: int,
                   meta_len: int, seg_bytes: int, ack_every: int,
                   trace_id: int = 0) -> None:
        t0 = time.perf_counter_ns() if trace_id \
            and _spans.recorder is not None else 0
        meta = wire_pool.acquire(meta_len)
        try:
            if not _recv_exact_into(conn, meta):
                raise OSError("peer closed mid-frame (meta)")

            def fill(view: memoryview) -> None:
                # the zero-copy landing: segment bytes recv_into the
                # decoded payload's final buffers
                if not _recv_exact_into(conn, view):
                    raise OSError("peer closed mid-frame (segment)")

            payload = codec.decode(meta, fill)
        finally:
            wire_pool.release(meta)
        if t0:
            # a traced CTRL frame landing: the wire-level receive span
            # (header trace word u2), attributed to the request's trace
            r = _spans.recorder
            if r is not None:
                r.record("wire.ctrl", trace_id, t0,
                         time.perf_counter_ns(),
                         args={"src": src,
                               "bytes": _HDR.size + meta_len + seg_bytes})
        ack_now = None
        with self._ilock:
            self._rx_account(src, _HDR.size + meta_len + seg_bytes, False)
            if seq <= self._seen.get(src, 0):
                self.dup_frames += 1         # replay overlap: suppress
            else:
                self._seen[src] = seq
                self._inbox.append((tag, src, payload))
            ack_now = self._ack_bookkeeping(src, ack_every)
        if ack_now is not None:
            self._send_ack(src, ack_now)

    def _recv_data(self, conn: socket.socket, flags: int, src: int,
                   seq: int, get_id: int, offset: int, nbytes: int,
                   ack_every: int) -> None:
        meta = None
        extra = 0
        if flags & F_FIRST:
            mlen_buf = bytearray(4)
            if not _recv_exact_into(conn, memoryview(mlen_buf)):
                raise OSError("peer closed mid-frame (frag meta len)")
            mlen = _U32.unpack(mlen_buf)[0]
            mbuf = wire_pool.acquire(mlen)
            try:
                if not _recv_exact_into(conn, mbuf):
                    raise OSError("peer closed mid-frame (frag meta)")
                meta = codec.decode_with_segments(bytes(mbuf), [])
            finally:
                wire_pool.release(mbuf)
            extra = 4 + mlen
        with self._ilock:
            dup = seq <= self._seen.get(src, 0)
        committed = False
        dups = 0    # counted locally, published under _ilock below (the
        # increment is a read-modify-write racing other receive threads)
        if dup:
            dups += 1
            if not _drain(conn, nbytes):
                raise OSError("peer closed mid-frame (dup frag)")
        else:
            lv = self.landing_view
            mv = lv(get_id, src, offset, nbytes, meta) if lv else None
            if mv is None:
                # stale fragment (its GET already completed, or no engine
                # attached yet): consume and discard
                if not _drain(conn, nbytes):
                    raise OSError("peer closed mid-frame (stale frag)")
            else:
                # a receive that dies here leaves NO landed mark, so the
                # peer's replay (same offset, fresh connection) re-lands
                # it; if that replay raced us and committed first, our
                # identical bytes were idempotent and we stand down
                if not _recv_exact_into(conn, mv):
                    raise OSError("peer closed mid-frame (frag body)")
                eng = getattr(lv, "__self__", None)   # bound engine method
                committed = eng is not None and \
                    eng.landing_commit(get_id, offset)
                if not committed:
                    dups += 1
        ack_now = None
        with self._ilock:
            self.dup_frames += dups
            self._rx_account(src, _HDR.size + extra + nbytes, True)
            if not dup:
                self._seen[src] = max(self._seen.get(src, 0), seq)
                if committed:
                    self._inbox.append((AM_TAG_GET_FRAG, src,
                                        (get_id, offset, nbytes, None,
                                         None)))
            ack_now = self._ack_bookkeeping(src, ack_every)
        if ack_now is not None:
            self._send_ack(src, ack_now)

    def _ack_bookkeeping(self, src: int, ack_every: int) -> int | None:
        """Caller holds ``_ilock``; returns the seq to ack now, if due."""
        n = self._unacked_in.get(src, 0) + 1
        if n >= ack_every:
            self._unacked_in[src] = 0
            return self._seen.get(src, 0)
        self._unacked_in[src] = n
        return None

    def _prune_unacked(self, src: int, upto: int) -> None:
        with self._plock:
            ent = self._peers.get(src)
        if ent is None:
            return
        with ent[1]:
            q = ent[3]
            while q and q[0][0] <= upto:
                q.popleft()

    def _send_ack(self, src: int, upto: int) -> None:
        """Best-effort cumulative ack (idempotent: never replayed; a lost
        ack just leaves the peer's window larger until the next one).
        Runs on a receive thread, so a missing reverse connection gets only
        a SHORT connect budget — stalling reception behind a 30s boot retry
        would freeze frames already queued on this connection.  A failed
        send DROPS the socket (the next ack reconnects) and never declares
        the peer dead — a receive-only rank's ack channel would otherwise
        stay wedged after one reset and starve the sender's window."""
        with self._plock:
            ent = self._peers.get(src)
            if ent is None:
                ent = self._peers[src] = [None, threading.Lock(), 0, deque()]
        ack = (_HDR.pack(K_ACK, 0, 0, self.rank, upto, 0, 0, 0)
               if self.binary else _frame(("a", self.rank, upto)))
        with ent[1]:
            try:
                if ent[0] is None:
                    ent[0] = self._connect(src, retry_s=2.0,
                                           report_dead=False)
                ent[0].sendall(ack)
            except OSError:
                if ent[0] is not None:
                    try:
                        ent[0].close()
                    except OSError:
                        pass
                    ent[0] = None

    # --------------------------------------------------------------- send
    def _connect(self, dst: int, retry_s: float = 30.0,
                 report_dead: bool = True) -> socket.socket:
        """Connect to ``dst``, retrying refusals for up to ``retry_s`` (30s
        default covers peers still booting; reconnect paths pass a short
        budget — a peer dead mid-run should fail fast, not hang callers for
        the boot window).  Bails immediately on fabric teardown.
        ``report_dead=False`` suppresses the peer-death notification —
        best-effort paths (acks) must not declare a live peer dead off a
        short transient budget."""
        deadline = time.monotonic() + retry_s
        while True:
            if self._stop.is_set():
                raise OSError("fabric is shutting down")
            try:
                s = socket.create_connection(
                    (self.hosts[dst], self.base_port + dst), timeout=2.0)
                break
            except OSError:
                if time.monotonic() > deadline:
                    if report_dead:
                        self._peer_dead(dst)
                    raise
                time.sleep(0.05)   # peer still booting
        _tune_socket(s)
        return s

    def _peer_dead(self, dst: int) -> None:
        """A peer is unreachable past its retry budget: tell the engine so
        it can release resources pinned for that rank (registered-buffer
        shares via ``CommEngine.on_peer_failed``)."""
        cb = self.on_peer_dead
        if cb is not None:
            try:
                cb(dst)
            except Exception:       # a GC hook must never mask the OSError
                pass

    def deliver(self, dst: int, tag: int, src: int, payload: Any,
                trace_id: int = 0) -> None:
        if dst == self.rank:
            with self._ilock:
                self._inbox.append((tag, src, payload))
            return
        # the expensive serialization (payload object graph) runs OUTSIDE
        # the send lock; only the tiny seq-stamped header is built inside
        if self.binary:
            meta, segs = codec.encode(payload)
            seg_bytes = sum(memoryview(s).nbytes for s in segs)
            tid = trace_id & 0xFFFFFFFFFFFFFFFF

            def frame(seq: int) -> list:
                return [_HDR.pack(K_CTRL, 0, tag, src, seq,
                                  len(meta), seg_bytes, tid), meta, *segs]
            self._send_frame(dst, frame,
                             _HDR.size + len(meta) + seg_bytes, frag=False,
                             snapshot=True)
            return
        body = pickle.dumps((tag, src, payload),
                            protocol=pickle.HIGHEST_PROTOCOL)
        self._send_frame(dst, lambda seq: [_frame(("d", seq, body))], None,
                         frag=False)

    def deliver_data(self, dst: int, get_id: int, offset: int, nbytes: int,
                     data: Any, meta: dict | None, last: bool) -> None:
        """Ship one rendezvous GET fragment as a binary DATA frame whose
        raw bytes go scatter-gather straight from the registered buffer."""
        flags = (F_FIRST if meta is not None else 0) | (F_LAST if last else 0)
        head: list = []
        if meta is not None:
            mblob, msegs = codec.encode(meta)
            assert not msegs, "fragment meta must be segment-free"
            head = [_U32.pack(len(mblob)), mblob]
        extra = sum(len(b) for b in head)

        def frame(seq: int) -> list:
            return [_HDR.pack(K_DATA, flags, 0, self.rank, seq,
                              get_id, offset, nbytes), *head, data]
        self._send_frame(dst, frame, _HDR.size + extra + nbytes, frag=True)

    def _send_frame(self, dst: int, frame, nbytes: int | None,
                    frag: bool, snapshot: bool = False) -> None:
        """Seq-stamp, window, account, and transmit one frame (binary
        scatter-gather list or legacy pre-framed bytes).

        ``snapshot=True`` stores byte COPIES of the frame's buffers in the
        replay window while still transmitting the zero-copy views: a CTRL
        payload's arrays may be mutated by the caller after ``send_am``
        returns (the legacy pickle framing snapshotted implicitly), and a
        reconnect replay must resend the bytes as they were at send time.
        DATA frames skip it — their source is a registered buffer the
        engine contract keeps immutable until the GET completes."""
        with self._plock:
            ent = self._peers.get(dst)
            if ent is None:
                ent = self._peers[dst] = [None, threading.Lock(), 0, deque()]
        with ent[1]:     # frames must not interleave on one connection
            if len(ent[3]) >= _params.get("comm_socket_replay_window"):
                raise RuntimeError(
                    f"rank {self.rank}: replay window to rank {dst} full "
                    f"({len(ent[3])} unacked frames) — peer stopped acking")
            ent[2] += 1
            seq = ent[2]
            bufs = frame(seq)
            if nbytes is None:
                nbytes = sum(len(b) for b in bufs)
            # bytes_sent/peer_tx are shared across peers; concurrent
            # senders hold different per-peer locks, so the
            # read-modify-write needs the peer-table lock
            with self._plock:
                self.bytes_sent += nbytes
                tx = self.peer_tx.get(dst)
                if tx is None:
                    tx = self.peer_tx[dst] = [0, 0, 0]
                tx[0] += nbytes
                tx[1] += 1
                if frag:
                    tx[2] += 1
            if snapshot:
                ent[3].append((seq, [bytes(memoryview(b).cast("B"))
                                     for b in bufs]))
            else:
                ent[3].append((seq, bufs))
            if ent[0] is None:
                ent[0] = self._connect(dst)
            if (self._fault_rng is not None
                    and self._fault_rng.random() < self._fault_p):
                # injected fault: hard-break the live connection so this
                # send fails and exercises reconnect-and-replay
                try:
                    ent[0].shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            try:
                _sendmsg_all(ent[0], bufs)
            except OSError:
                self._reconnect_and_replay(dst, ent)

    def _reconnect_and_replay(self, dst: int, ent: list) -> None:
        """A broken connection loses whatever TCP had buffered: reconnect
        and resend the whole unacked window in order (caller holds the
        send lock).  The receiver's seq dedup drops the overlap."""
        try:
            if ent[0] is not None:
                ent[0].close()
        except OSError:
            pass
        ent[0] = None
        self.replays += 1
        ent[0] = self._connect(dst, retry_s=5.0)
        for _seq, bufs in list(ent[3]):
            _sendmsg_all(ent[0], bufs)   # a second failure here is fatal

    def peer_stats(self) -> dict:
        """Per-peer traffic ledgers: ``{"tx"|"rx": {rank: {bytes, frames,
        frags}}}`` (the per-peer gauges surfaced in the ``comm`` block)."""
        with self._plock:
            tx = {d: {"bytes": v[0], "frames": v[1], "frags": v[2]}
                  for d, v in self.peer_tx.items()}
        with self._ilock:
            rx = {s: {"bytes": v[0], "frames": v[1], "frags": v[2]}
                  for s, v in self.peer_rx.items()}
        return {"tx": tx, "rx": rx}

    # ----------------------------------------------------- drain (local)
    def drain(self, rank: int, limit: int = 64) -> list[tuple]:
        assert rank == self.rank
        out = []
        with self._ilock:
            while self._inbox and len(out) < limit:
                out.append(self._inbox.popleft())
        return out

    def pending(self, rank: int) -> int:
        assert rank == self.rank
        with self._ilock:
            return len(self._inbox)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._plock:
            for ent in self._peers.values():
                if ent[0] is not None:
                    try:
                        ent[0].shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        ent[0].close()
                    except OSError:
                        pass
            self._peers.clear()
            # shutdown() (not just close()) unblocks recv threads parked in
            # recv(2) — close alone only drops the fd reference while the
            # syscall keeps blocking — so _recv_main exits and no
            # thread/fd accumulates across fabric create/teardown cycles
            for conn in self._accepted:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            self._accepted.clear()


class SocketCommEngine(InprocCommEngine):
    """The engine vtable over :class:`SocketFabric`.

    :class:`SocketFabric` exposes the same deliver/drain/pending surface
    the in-process fabric does, so the whole AM + rendezvous-GET + barrier
    protocol is inherited verbatim — the engine cannot tell whether its
    bytes cross a deque or a TCP connection, which is exactly the vtable
    discipline the reference's comm engines follow
    (``parsec_comm_engine.h:176-199``)."""

    def __init__(self, fabric: SocketFabric) -> None:
        super().__init__(fabric, fabric.rank)
        # a rank unreachable past the reconnect budget releases its
        # registered-buffer shares (the peer-death GC)
        fabric.on_peer_dead = self.on_peer_failed
        # DATA-frame bytes land through the engine's zone registry from
        # the fabric's receive threads (recv_into the final destination)
        fabric.landing_view = self.landing_view

    def _plan_frags(self, value: Any) -> tuple | None:
        # fragmented rendezvous needs the binary DATA frames; the legacy
        # pickle framing keeps the monolithic replies it always had
        if not self.fabric.binary:
            return None
        return super()._plan_frags(value)

    def _transport_frag(self, dst: int, get_id: int, offset: int,
                        nbytes: int, data: Any, meta: dict | None,
                        last: bool) -> None:
        if dst == self.rank:
            super()._transport_frag(dst, get_id, offset, nbytes, data,
                                    meta, last)
            return
        self.fabric.deliver_data(dst, get_id, offset, nbytes, data, meta,
                                 last)

    def fini(self) -> None:
        super().fini()          # force-drop leftover registrations first
        self.fabric.close()
