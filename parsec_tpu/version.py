"""Version info for parsec-tpu."""

__version__ = "0.1.0"
API_VERSION = (4, 0)  # tracks the reference API generation (parsec runtime.h v4.0)
