"""Pluggable schedulers (rebuild of ``parsec/mca/sched/``)."""

from .api import SchedulerModule

_registered = False


def ensure_registered() -> None:
    """Import-time component registration, idempotent."""
    global _registered
    if not _registered:
        from . import modules  # noqa: F401
        _registered = True


__all__ = ["SchedulerModule", "ensure_registered"]
