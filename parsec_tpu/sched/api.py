"""Scheduler component interface.

Rebuild of ``parsec/mca/sched/sched.h:183-353``: a scheduler module exposes
``install / flow_init / schedule / select / remove``.  The *distance* contract
(``sched.h:22-170``) is preserved: ``schedule(es, tasks, distance)`` hints how
far from the submitting stream the tasks should land (0 = hot, larger = was
rescheduled / overflowed), and ``select`` returns the distance the task came
from so starvation pushes work outward fairly.
"""

from __future__ import annotations

from typing import Any, Sequence


class SchedulerModule:
    name = "base"

    def install(self, context: Any) -> None:
        """Global structures; called once per context."""

    def flow_init(self, es: Any) -> None:
        """Per-execution-stream structures; called from each worker before
        the barrier opens (cf. ``flow_init`` rendezvous)."""

    def schedule(self, es: Any, tasks: Sequence[Any], distance: int = 0) -> None:
        raise NotImplementedError

    def select(self, es: Any) -> tuple[Any | None, int]:
        """Return (task, distance) or (None, 0).

        Distance contract: 0 = the stream's own queue; 1..98 = pulled from
        another stream's queue, topologically-near first (a *steal* — the
        SELECT_STEAL PINS feed); 99 = the shared system queue (externally
        submitted work; starvation relief, not a steal)."""
        raise NotImplementedError

    def remove(self, context: Any) -> None:
        """Tear down; must leave no queued tasks behind."""

    def pending_tasks(self, context: Any) -> int:
        """Approximate queue depth (PAPI-SDE counter analog)."""
        return -1

    def queue_depths(self, context: Any) -> dict[str, int]:
        """Best-effort per-queue depth map for diagnostics (the flight
        recorder's stall dump).  Modules with per-stream queues override
        this; the base reports the shared total only."""
        return {"shared": self.pending_tasks(context)}
