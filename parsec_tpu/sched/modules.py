"""Scheduler implementations.

Rebuild of the reference's scheduler zoo (``parsec/mca/sched/*``, SURVEY
§2.4), all eleven: **lfq** (default) per-stream bounded buffers spilling to
a per-VP overflow dequeue, with sibling stealing; **ap** global
absolute-priority list; **spq** global priority+distance list (the tutorial
scheduler, ``sched.h:87-169``); **gd** global dequeue; **ll/llp** per-stream
LIFOs with stealing (± priority); **rnd** random; **ip** inverse priority;
and the local-hierarchical family — **pbq** priority-based local queues with
proximity-ordered stealing, **ltq** local tree queues whose steals migrate
whole release-subtrees, **lhq** local hierarchical queues with an
intermediate group rung.  Priorities and the distance contract follow
``sched/api.py``.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
from collections import deque
from typing import Any, Sequence

from ..core.params import params as _params
from ..core.hbbuffer import HBBuffer, StealDeque
from ..core.mca import Component, component
# imported at module load (main thread): the topology affinity snapshot
# must be taken before any worker binds itself to a single core
from ..core import topology as _topology
from .api import SchedulerModule

_params.register("sched_lfq_buffer_size", 256,
                 "per-stream sharded-deque capacity for lfq (spills to the "
                 "per-VP system queue beyond this; large enough that a "
                 "release batch stays on the lock-free local path)")


def _task_priority(t: Any) -> int:
    return t.priority


def _stream_queue_depths(context: Any) -> dict[str, int]:
    """Shared per-stream depth map (lfq/pbq family shapes) for the
    flight-recorder stall dump."""
    out: dict[str, int] = {}
    for vp in context.virtual_processes:
        if vp.sched_private is not None and \
                hasattr(vp.sched_private, "system"):
            out[f"vp{vp.vp_id}.system"] = len(vp.sched_private.system)
        for es in vp.execution_streams:
            if es.sched_private is not None:
                try:
                    out[f"es{es.th_id}"] = len(es.sched_private)
                except TypeError:
                    pass
    return out


# ---------------------------------------------------------------------------
# lfq — local flat queues (default; cf. sched/lfq, priority 20)
# ---------------------------------------------------------------------------

class _VPQueues:
    def __init__(self) -> None:
        self.system = deque()
        self.lock = threading.Lock()


class LFQModule(SchedulerModule):
    """Sharded ready queues: the per-ES :class:`StealDeque` is the primary
    push target — owner push/pop are GIL-atomic deque operations with no
    lock, and a lock is taken only on steal, overflow spill, or the
    priority-scan degradation (core/hbbuffer.py).  Cross-worker contention
    on the common select→release path is therefore zero."""

    name = "lfq"

    def install(self, context: Any) -> None:
        for vp in context.virtual_processes:
            vp.sched_private = _VPQueues()
        self._cap = _params.get("sched_lfq_buffer_size")

    def flow_init(self, es: Any) -> None:
        vpq = es.virtual_process.sched_private

        def overflow(items: list, distance: int) -> None:
            with vpq.lock:
                vpq.system.extend(items)

        es.sched_private = StealDeque(self._cap, parent_push=overflow)

    def schedule(self, es: Any, tasks: Sequence[Any], distance: int = 0) -> None:
        sp = es.sched_private
        if sp is None or distance > 0:
            vpq = es.virtual_process.sched_private
            with vpq.lock:
                vpq.system.extend(tasks)
            return
        sp.push_all(tasks if type(tasks) is list else list(tasks), distance)

    def select(self, es: Any) -> tuple[Any | None, int]:
        sp = es.sched_private
        if sp is not None:
            t = sp.try_pop_best(priority=_task_priority)
            if t is not None:
                return t, 0
        # steal from sibling streams in the same VP (never across VPs)
        for sib in es.virtual_process.execution_streams:
            if sib is es or sib.sched_private is None:
                continue
            t = sib.sched_private.steal()
            if t is not None:
                return t, 1
        vpq = es.virtual_process.sched_private
        with vpq.lock:
            if vpq.system:
                return vpq.system.popleft(), 99
        return None, 0

    def remove(self, context: Any) -> None:
        for vp in context.virtual_processes:
            vp.sched_private = None
            for es in vp.execution_streams:
                es.sched_private = None

    def pending_tasks(self, context: Any) -> int:
        n = 0
        for vp in context.virtual_processes:
            if vp.sched_private is not None:
                n += len(vp.sched_private.system)
            for es in vp.execution_streams:
                if es.sched_private is not None:
                    n += len(es.sched_private)
        return n

    queue_depths = staticmethod(_stream_queue_depths)


# ---------------------------------------------------------------------------
# global single-queue family
# ---------------------------------------------------------------------------

class _GlobalHeapModule(SchedulerModule):
    """Shared helper: one process-global heap ordered by a key fn."""

    def install(self, context: Any) -> None:
        self._heap: list = []
        self._lock = threading.Lock()
        self._tie = itertools.count()

    def _key(self, task: Any, distance: int):
        raise NotImplementedError

    def schedule(self, es: Any, tasks: Sequence[Any], distance: int = 0) -> None:
        with self._lock:
            for t in tasks:
                heapq.heappush(self._heap,
                               (self._key(t, distance), next(self._tie), t))

    def select(self, es: Any) -> tuple[Any | None, int]:
        with self._lock:
            if not self._heap:
                return None, 0
            _, _, t = heapq.heappop(self._heap)
            return t, 0

    def remove(self, context: Any) -> None:
        self._heap = []

    def pending_tasks(self, context: Any) -> int:
        return len(self._heap)


class APModule(_GlobalHeapModule):
    """Absolute priority: highest priority first (cf. sched/ap)."""
    name = "ap"

    def _key(self, task: Any, distance: int):
        return (-task.priority,)


class SPQModule(_GlobalHeapModule):
    """Priority then distance (the documented tutorial scheduler, sched/spq)."""
    name = "spq"

    def _key(self, task: Any, distance: int):
        return (-task.priority, distance)


class IPModule(_GlobalHeapModule):
    """Inverse priority — lowest first (cf. sched/ip; a testing policy)."""
    name = "ip"

    def _key(self, task: Any, distance: int):
        return (task.priority,)


class GDModule(SchedulerModule):
    """Global dequeue (cf. sched/gd): hot tasks to the front."""
    name = "gd"

    def install(self, context: Any) -> None:
        self._dq = deque()
        self._lock = threading.Lock()

    def schedule(self, es: Any, tasks: Sequence[Any], distance: int = 0) -> None:
        with self._lock:
            if distance == 0:
                self._dq.extendleft(reversed(list(tasks)))
            else:
                self._dq.extend(tasks)

    def select(self, es: Any) -> tuple[Any | None, int]:
        with self._lock:
            if self._dq:
                return self._dq.popleft(), 0
        return None, 0

    def remove(self, context: Any) -> None:
        self._dq = deque()

    def pending_tasks(self, context: Any) -> int:
        return len(self._dq)


class RNDModule(SchedulerModule):
    """Random selection (cf. sched/rnd; a fairness fuzzer)."""
    name = "rnd"

    def install(self, context: Any) -> None:
        self._items: list = []
        self._lock = threading.Lock()
        self._rng = random.Random(0x9a53)

    def schedule(self, es: Any, tasks: Sequence[Any], distance: int = 0) -> None:
        with self._lock:
            self._items.extend(tasks)

    def select(self, es: Any) -> tuple[Any | None, int]:
        with self._lock:
            if not self._items:
                return None, 0
            i = self._rng.randrange(len(self._items))
            self._items[i], self._items[-1] = self._items[-1], self._items[i]
            return self._items.pop(), 0

    def remove(self, context: Any) -> None:
        self._items = []

    def pending_tasks(self, context: Any) -> int:
        return len(self._items)


# ---------------------------------------------------------------------------
# ll / llp — per-stream LIFOs with stealing (cf. sched/ll, sched/llp)
# ---------------------------------------------------------------------------

class LLModule(SchedulerModule):
    """Per-stream lock-free LIFOs with stealing.  When the native tier is
    up, the queue IS the C++ ABA-counted LIFO (the reference's ll is exactly
    its ``class/lifo.h``); tasks ride as uid handles through a side map.
    ``llp`` needs priority scans, so it stays on the Python deque.

    Steal order differs between tiers by design: the native LIFO can only
    pop from the top, so steals are LIFO (exactly the reference's ll, which
    steals via ``parsec_lifo_pop`` too); the Python tier steals FIFO from
    the victim's bottom for locality.  Both are valid ll semantics — the
    scheduler contract orders nothing across streams."""

    name = "ll"
    use_priority = False

    def install(self, context: Any) -> None:
        self._tasks: dict[int, Any] = {}
        self._native = None
        if not self.use_priority:
            try:
                from .. import native        # registers runtime_native
                if _params.get("runtime_native") and native.available():
                    self._native = native
            except Exception:
                self._native = None

    def flow_init(self, es: Any) -> None:
        if self._native is not None:
            es.sched_private = self._native.NativeLifo()
        else:
            es.sched_private = (deque(), threading.Lock())

    def schedule(self, es: Any, tasks: Sequence[Any], distance: int = 0) -> None:
        target = es if es.sched_private is not None else \
            es.virtual_process.execution_streams[0]
        if self._native is not None:
            lifo = target.sched_private
            for t in tasks:
                self._tasks[t.uid] = t
                lifo.push(t.uid)
            return
        dq, lock = target.sched_private
        with lock:
            dq.extend(tasks)

    def select(self, es: Any) -> tuple[Any | None, int]:
        streams = es.virtual_process.execution_streams
        order = [es] + [s for s in streams if s is not es]
        for dist, s in enumerate(order):
            if s.sched_private is None:
                continue
            if self._native is not None:
                uid = s.sched_private.pop()
                if uid is None:
                    continue
                t = self._tasks.pop(uid, None)
                if t is None:
                    continue   # remove() raced us during teardown
                return t, min(dist, 1)
            dq, lock = s.sched_private
            with lock:
                if not dq:
                    continue
                if self.use_priority and s is es:
                    best = max(range(len(dq)), key=lambda i: dq[i].priority)
                    t = dq[best]
                    del dq[best]
                    return t, 0
                # own queue: LIFO; victim: FIFO steal
                return (dq.pop() if s is es else dq.popleft()), min(dist, 1)
        return None, 0

    def remove(self, context: Any) -> None:
        for vp in context.virtual_processes:
            for es in vp.execution_streams:
                es.sched_private = None
        self._tasks = {}

    def pending_tasks(self, context: Any) -> int:
        n = 0
        for vp in context.virtual_processes:
            for es in vp.execution_streams:
                if es.sched_private is None:
                    continue
                if self._native is not None:
                    n += len(es.sched_private)
                else:
                    n += len(es.sched_private[0])
        return n


class LLPModule(LLModule):
    name = "llp"
    use_priority = True


# ---------------------------------------------------------------------------
# the local-hierarchical family: pbq / ltq / lhq
# (cf. sched_local_queues_utils.h: per-stream hbbuffer "task_queue", an
#  ordered list of hierarch queues to steal from, and a shared system
#  dequeue.  hwloc proximity becomes th_id ring distance here — the GIL
#  flattens cache hierarchy, the *structure* is what is rebuilt.)
# ---------------------------------------------------------------------------

class PBQModule(SchedulerModule):
    """Priority-based local queues (``mca/sched/pbq``): per-stream bounded
    buffer with best-priority pop, nearest-neighbor steal order, shared
    system dequeue."""

    name = "pbq"

    def install(self, context: Any) -> None:
        self._order: dict[int, list] = {}   # id(es) -> cached steal order
        for vp in context.virtual_processes:
            vp.sched_private = _VPQueues()
            # reference queue_size = 4 * vp->nb_cores — per VP
            vp.sched_private.cap = max(4, 4 * len(vp.execution_streams))

    def flow_init(self, es: Any) -> None:
        vpq = es.virtual_process.sched_private

        def overflow(items: list, distance: int) -> None:
            with vpq.lock:
                vpq.system.extend(items)

        es.sched_private = HBBuffer(vpq.cap, parent_push=overflow)

    def _steal_order(self, es: Any) -> list:
        order = self._order.get(id(es))
        if order is None:
            sibs = es.virtual_process.execution_streams
            n = len(sibs)
            me = sibs.index(es)
            my_core = _topology.core_of_stream(es.th_id)
            idx = {id(s): i for i, s in enumerate(sibs)}
            # topology-near first (same LLC before cross-cache — the
            # hwloc distance matrix), ring distance as the tiebreak;
            # static per stream, so computed once and cached
            order = sorted(
                (s for s in sibs if s is not es),
                key=lambda s: (
                    _topology.distance(my_core,
                                       _topology.core_of_stream(s.th_id)),
                    min((idx[id(s)] - me) % n,
                        (me - idx[id(s)]) % n)))
            self._order[id(es)] = order
        return order

    def schedule(self, es: Any, tasks: Sequence[Any],
                 distance: int = 0) -> None:
        if es.sched_private is None or distance > 0:
            vpq = es.virtual_process.sched_private
            with vpq.lock:
                vpq.system.extend(tasks)
            return
        es.sched_private.push_all(list(tasks), distance)

    def select(self, es: Any) -> tuple[Any | None, int]:
        if es.sched_private is not None:
            t = es.sched_private.try_pop_best(priority=_task_priority)
            if t is not None:
                return t, 0
            for d, sib in enumerate(self._steal_order(es)):
                if sib.sched_private is None:
                    continue
                t = sib.sched_private.steal()
                if t is not None:
                    return t, min(1 + d, 98)   # 99 is the system sentinel
        vpq = es.virtual_process.sched_private
        with vpq.lock:
            if vpq.system:
                return vpq.system.popleft(), 99
        return None, 0

    def remove(self, context: Any) -> None:
        for vp in context.virtual_processes:
            vp.sched_private = None
            for es in vp.execution_streams:
                es.sched_private = None

    def pending_tasks(self, context: Any) -> int:
        n = 0
        for vp in context.virtual_processes:
            if vp.sched_private is not None:
                n += len(vp.sched_private.system)
            for es in vp.execution_streams:
                if es.sched_private is not None:
                    n += len(es.sched_private)
        return n

    queue_depths = staticmethod(_stream_queue_depths)


class _Bundle:
    """A released batch kept together — the maxheap node of ltq: the owner
    pops the best task off the top; a thief migrates the whole remainder
    (subtree stealing)."""

    __slots__ = ("tasks",)

    def __init__(self, tasks: list) -> None:
        self.tasks = sorted(tasks, key=lambda t: t.priority, reverse=True)

    @property
    def priority(self) -> int:
        return self.tasks[0].priority if self.tasks else -1


class LTQModule(PBQModule):
    """Local tree queues (``mca/sched/ltq``): releases travel as heaps —
    one steal migrates a whole subtree of related work, preserving the
    producer-consumer locality the tree encodes."""

    name = "ltq"

    def schedule(self, es: Any, tasks: Sequence[Any],
                 distance: int = 0) -> None:
        if not tasks:
            return
        super().schedule(es, [_Bundle(list(tasks))], distance)

    def select(self, es: Any) -> tuple[Any | None, int]:
        b, d = super().select(es)
        if b is None:
            return None, 0
        t = b.tasks.pop(0)
        if b.tasks and es.sched_private is not None:
            # remainder stays with whoever popped it (subtree migration)
            es.sched_private.push_all([b], 0)
        return t, d

    def pending_tasks(self, context: Any) -> int:
        n = 0
        for vp in context.virtual_processes:
            if vp.sched_private is not None:
                n += sum(len(b.tasks) for b in vp.sched_private.system)
            for es in vp.execution_streams:
                if es.sched_private is not None:
                    n += sum(len(b.tasks) for b in es.sched_private._items)
        return n


class LHQModule(PBQModule):
    """Local hierarchical queues (``mca/sched/lhq``): an intermediate
    *group* buffer between the per-stream buffers and the system queue —
    the hwloc-level ladder with two rungs (stream → group → VP)."""

    name = "lhq"

    def install(self, context: Any) -> None:
        super().install(context)
        self._group: dict[int, Any] = {}   # id(es) -> its group buffer
        for vp in context.virtual_processes:
            # one group buffer per last-level cache represented among this
            # VP's streams (the real hwloc rung; a VP whose streams all
            # share one LLC gets one group — no artificial split)
            vpq = vp.sched_private
            llcs = sorted({_topology.llc_group_of(
                _topology.core_of_stream(s.th_id))
                for s in vp.execution_streams})
            vpq.llc_index = {llc: i for i, llc in enumerate(llcs)}
            vpq.groups = []
            for _g in llcs:
                def spill(items: list, distance: int, vpq=vpq) -> None:
                    with vpq.lock:
                        vpq.system.extend(items)
                vpq.groups.append(HBBuffer(vpq.cap, parent_push=spill))

    def _group_of(self, es: Any):
        grp = self._group.get(id(es))
        if grp is None:
            vpq = es.virtual_process.sched_private
            g = vpq.llc_index[_topology.llc_group_of(
                _topology.core_of_stream(es.th_id))]
            grp = vpq.groups[g]
            self._group[id(es)] = grp
        return grp

    def flow_init(self, es: Any) -> None:
        vpq = es.virtual_process.sched_private

        def overflow(items: list, distance: int) -> None:
            self._group_of(es).push_all(items, distance)

        es.sched_private = HBBuffer(vpq.cap, parent_push=overflow)

    def select(self, es: Any) -> tuple[Any | None, int]:
        if es.sched_private is not None:
            t = es.sched_private.try_pop_best(priority=_task_priority)
            if t is not None:
                return t, 0
            my_grp = self._group_of(es)
            # the stream's OWN hierarchy: its buffer's spill target is not
            # another stream's queue, so this is distance 0 (not a steal)
            t = my_grp.try_pop_best(priority=_task_priority)
            if t is not None:
                return t, 0
            for d, sib in enumerate(self._steal_order(es)):
                if sib.sched_private is None:
                    continue
                t = sib.sched_private.steal()
                if t is not None:
                    return t, min(1 + d, 98)
            vpq = es.virtual_process.sched_private
            for grp in vpq.groups:
                if grp is my_grp:
                    continue    # already drained above; a re-pop is no steal
                t = grp.steal()
                if t is not None:
                    return t, 10
        vpq = es.virtual_process.sched_private
        with vpq.lock:
            if vpq.system:
                return vpq.system.popleft(), 99
        return None, 0

    def pending_tasks(self, context: Any) -> int:
        n = super().pending_tasks(context)
        for vp in context.virtual_processes:
            if getattr(vp.sched_private, "groups", None):
                n += sum(len(g) for g in vp.sched_private.groups)
        return n


# ---------------------------------------------------------------------------
# component registrations (priorities mirror the reference's)
# ---------------------------------------------------------------------------

def _mk_component(mod_cls: type, prio: int) -> None:
    @component
    class _C(Component):
        type_name = "sched"
        name = mod_cls.name
        priority = prio

        def open(self, context: Any = None) -> SchedulerModule:
            return mod_cls()

    _C.__name__ = f"Sched{mod_cls.name.upper()}Component"


@component
class SchedServeFairComponent(Component):
    """``--mca sched serve_fair`` / ``Context(scheduler="serve_fair")``:
    a context built with the serving layer's weighted-fair shim
    (serve/fair.py) pre-installed around whichever module wins the normal
    priority query.  Fairness applies only to tasks of pools carrying a
    serve submission — i.e. this exists to hand a pre-shimmed context to
    ``RuntimeServer(context=...)`` (which then reuses it instead of
    stacking a second shim); pools enqueued outside a server delegate
    straight through to the inner module and are dispatched FIRST.
    Explicit request only: the shim taxes schedule/select with a fairness
    probe, so it must never win a default query."""

    type_name = "sched"
    name = "serve_fair"
    priority = 0

    def query(self, context: Any = None) -> bool:
        return False

    def open(self, context: Any = None) -> SchedulerModule:
        from ..core.mca import repository
        from ..serve.fair import FairScheduler
        # best-priority inner by direct scan (not repository.query: the
        # sched MCA param may name serve_fair itself, which would recurse)
        for c in repository.components_of_type("sched"):
            if c is not self and c.query(context):
                return FairScheduler(c.open(context))
        raise LookupError("serve_fair: no inner sched component accepts "
                          "this context")


_mk_component(LFQModule, 20)
_mk_component(SPQModule, 18 - 6)   # spq=12 in the reference
_mk_component(APModule, 12)
_mk_component(GDModule, 10)
_mk_component(PBQModule, 4)
_mk_component(LTQModule, 3)
_mk_component(LHQModule, 3)
_mk_component(LLModule, 2)
_mk_component(LLPModule, 2)
_mk_component(RNDModule, 1)
_mk_component(IPModule, 0)
