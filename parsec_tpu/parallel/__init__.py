"""Parallelism packs: SPMD lowerings + distributed schedule recipes
(SURVEY §2.12: DP/TP/PP/SP/EP as first-class derived schedules)."""

from . import expert, pipeline, train

__all__ = ["train", "pipeline", "expert"]
