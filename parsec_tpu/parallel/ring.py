"""Ring attention: sequence parallelism over the ICI ring.

The compiled (SPMD) realization of SURVEY §5.7's halo/ring dataflow: the
reference's closest structure is the 1-D stencil's neighbor exchange
(``tests/apps/stencil/stencil_1D.jdf:13-58``); for long-context attention
the same ring becomes blockwise KV rotation with online-softmax
accumulation (Ring Attention; the flash-attention recurrence distributed
over devices).

TPU-first design: ``shard_map`` over a ``sp`` mesh axis; each step computes
one [q-block × kv-block] attention partial on the MXU while
``lax.ppermute`` rotates the KV block to the next neighbor over ICI — XLA
overlaps the permute with the matmul, which is exactly the
communication/computation overlap the reference engineers by hand with
streams and MPI (SURVEY §3.4/§3.5).

Numerics: the online softmax keeps running (max, sum, out) per query row —
mathematically identical to dense softmax(QKᵀ)V up to float reassociation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ._compat import shard_map

_NEG_INF = -1e30


def _block_attention(q, k, v, mask):
    """One [q-block, kv-block] partial: scores, max, exp-weights, pv.

    q: [b, h, nq, d]; k/v: [b, h, nk, d]; mask: [nq, nk] additive.
    Returns (scores_max [b,h,nq], p_sum [b,h,nq], pv [b,h,nq,d]).
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = s + mask
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    return m, l, pv


def ring_attention_local(q, k, v, axis_name: str = "sp",
                         causal: bool = False):
    """Per-shard ring attention body (call under ``shard_map``).

    q/k/v: [b, h, n_local, d] — the sequence axis is sharded over
    ``axis_name``.  Rotates KV blocks ``axis_size`` times; accumulates with
    the online-softmax recurrence.  Returns [b, h, n_local, d] (same
    sharding as q).
    """
    n_dev = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, n_loc, d = q.shape
    q_pos = my * n_loc + jnp.arange(n_loc)

    def accumulate(acc, t, k_blk, v_blk):
        o, m, l = acc
        src = (my - t) % n_dev                   # block currently held
        if causal:
            k_pos = src * n_loc + jnp.arange(n_loc)
            mask = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0,
                             _NEG_INF).astype(jnp.float32)
        else:
            mask = jnp.zeros((n_loc, n_loc), jnp.float32)
        bm, bl, bpv = _block_attention(q, k_blk, v_blk, mask)
        m_new = jnp.maximum(m, bm)
        corr = jnp.exp(m - m_new)
        bcorr = jnp.exp(bm - m_new)
        l = l * corr + bl * bcorr
        o = o * corr[..., None] + bpv * bcorr[..., None]
        return (o, m_new, l)

    # t = 0: own block, no rotation yet
    acc0 = (jnp.zeros((b, h, n_loc, d), jnp.float32),
            jnp.full((b, h, n_loc), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, n_loc), jnp.float32))
    acc0 = accumulate(acc0, 0, k, v)

    def step(carry, t):
        o, m, l, k_blk, v_blk = carry
        # rotate KV to the next neighbor first (receive from the previous):
        # after t rotations we hold block (my - t) % n_dev — rotating at
        # the top of the body gives exactly n_dev-1 permutes total
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        o, m, l = accumulate((o, m, l), t, k_blk, v_blk)
        return (o, m, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = lax.scan(step, acc0 + (k, v),
                                  jnp.arange(1, n_dev))
    # rows with no visible keys (can't happen for causal with t>=1) keep l=0
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "sp",
                        causal: bool = False, batch_axis: str | None = "dp",
                        head_axis: str | None = "tp"):
    """Jitted ring attention over ``mesh``: q/k/v [b, h, n, d] with batch
    sharded on ``batch_axis``, heads on ``head_axis``, sequence on
    ``axis_name``."""
    spec = P(batch_axis, head_axis, axis_name, None)

    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return jax.jit(fn)


def dense_attention(q, k, v, causal: bool = False):
    """Reference dense softmax attention (correctness oracle)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        n = q.shape[2]
        mask = jnp.where(jnp.tril(jnp.ones((n, n), bool)), 0.0, _NEG_INF)
        s = s + mask
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
