"""Ulysses-style all-to-all sequence<->head re-sharding.

SURVEY §5.7: the reference's primitive for axis swaps is the generic
redistribute taskpool (``redistribute.jdf``); on TPU the compiled
equivalent of "re-shard the sequence axis into the head axis" is a single
``lax.all_to_all`` over the sequence-parallel mesh axis — one ICI
all-to-all instead of a task graph.

With ``x: [b, n_local, h, d]`` sharded on ``sp`` over the sequence axis,
:func:`seq_to_heads` returns ``[b, n, h_local, d]`` sharded on ``sp`` over
heads — each device then holds *full sequences for a subset of heads*
(the DeepSpeed-Ulysses layout), so ordinary dense attention runs locally.
:func:`heads_to_seq` is the inverse.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ._compat import shard_map


def seq_to_heads_local(x, axis_name: str = "sp"):
    """[b, n_loc, h, d] -> [b, n, h/axis, d] (call under shard_map)."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq_local(x, axis_name: str = "sp"):
    """[b, n, h_loc, d] -> [b, n/axis, h, d] (call under shard_map)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def make_ulysses_attention(mesh: Mesh, attention_fn,
                           axis_name: str = "sp",
                           batch_axis: str | None = "dp"):
    """Sequence-parallel attention by head re-sharding: all-to-all the
    sharded sequence into sharded heads, run ``attention_fn(q, k, v)``
    densely per head group, all-to-all back.

    ``attention_fn`` operates on [b, h_group, n_full, d] — e.g.
    :func:`parsec_tpu.parallel.ring.dense_attention`.
    """
    seq_spec = P(batch_axis, None, axis_name, None)   # [b, h, n, d] on seq

    def local(q, k, v):
        # to head-sharded layout: [b, h, n, d] -> [b, n, h, d] for the
        # collective, then back
        def to_heads(t):
            t = t.transpose(0, 2, 1, 3)               # [b, n_loc, h, d]
            t = seq_to_heads_local(t, axis_name)      # [b, n, h_loc, d]
            return t.transpose(0, 2, 1, 3)            # [b, h_loc, n, d]

        def to_seq(t):
            t = t.transpose(0, 2, 1, 3)               # [b, n, h_loc, d]
            t = heads_to_seq_local(t, axis_name)      # [b, n_loc, h, d]
            return t.transpose(0, 2, 1, 3)            # [b, h, n_loc, d]

        return to_seq(attention_fn(to_heads(q), to_heads(k), to_heads(v)))

    fn = shard_map(local, mesh=mesh,
                   in_specs=(seq_spec, seq_spec, seq_spec),
                   out_specs=seq_spec, check_vma=False)
    return jax.jit(fn)
