"""Pipeline parallelism (PP), both incarnations.

SURVEY §2.12's missing recipe, in the two forms the framework supports:

1. **On the dataflow core** (:func:`pipeline_ptg`): the Ex03 chain shape
   (``/root/reference/examples/Ex03_ChainMPI.jdf`` — a task chain whose
   affinity walks the ranks) widened into a stage × microbatch grid.  Task
   ``P(s, m)`` runs stage ``s`` on microbatch ``m``, lives on the rank that
   owns stage ``s`` (a 1-D cyclic stage distribution), receives its
   activation from ``P(s-1, m)`` and feeds ``P(s+1, m)`` — so activations
   hop rank to rank through the remote-dep protocol exactly like the
   reference's chain hops nodes over MPI.  Microbatch priority gives the
   interleaved 1F1B-ish fill: early microbatches drain ahead so every stage
   keeps busy.

2. **On the mesh** (:func:`make_pipeline_step`): the TPU-native schedule —
   stages are a ``pp`` mesh axis, weights shard per-stage, and the GPipe
   rotation runs as a ``lax.scan`` over ``nmicro + nstages - 1`` ticks with
   a ``ppermute`` handing each stage's activation to its successor over
   ICI.  No per-tick host dispatch: the whole pipeline is one XLA program.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from .. import ptg
from ..data_dist.matrix import VectorTwoDimCyclic

__all__ = ["pipeline_ptg", "make_pipeline_step"]


# ---------------------------------------------------------------------------
# 1. the dataflow-core recipe
# ---------------------------------------------------------------------------

def pipeline_ptg(X: Any, stage_fns: Sequence[Callable], nranks: int,
                 name: str = "pipeline") -> "ptg.PTGTaskpool":
    """Stage-chain PTG: ``X(m)`` microbatch tiles flow through every stage.

    ``X`` is the microbatch collection (inputs read from it, final outputs
    written back to it, home rank 0); ``stage_fns[s]`` is a pure
    ``ndarray -> ndarray`` applied by stage ``s``, which runs on rank
    ``s % nranks`` (the cyclic stage distribution the reference's Ex03
    ``rank_of`` plays with).
    """
    S = len(stage_fns)
    stages = VectorTwoDimCyclic(f"{name}_stages", lm=S, mb=1, P=nranks)

    p = ptg.PTGBuilder(name, X=X, STAGES=stages, S=S, M=X.mt,
                       FNS=tuple(stage_fns))
    t = p.task("P",
               s=ptg.span(0, lambda g, l: g.S - 1),
               m=ptg.span(0, lambda g, l: g.M - 1))
    t.affinity("STAGES", lambda g, l: (l.s,))
    # drain early microbatches first so stages stay busy (1F1B-ish fill)
    t.priority(lambda g, l: g.M - l.m)
    f = t.flow("V", ptg.RW)
    f.input(data=("X", lambda g, l: (l.m, 0)), guard=lambda g, l: l.s == 0)
    f.input(pred=("P", "V", lambda g, l: {"s": l.s - 1, "m": l.m}),
            guard=lambda g, l: l.s > 0)
    f.output(succ=("P", "V", lambda g, l: {"s": l.s + 1, "m": l.m}),
             guard=lambda g, l: l.s < g.S - 1)
    f.output(data=("X", lambda g, l: (l.m, 0)),
             guard=lambda g, l: l.s == g.S - 1)

    def body(es, task, g, l):
        v = task.flow_data("V")
        v.value = np.asarray(g.FNS[l.s](np.asarray(v.value)))
        v.version += 1

    t.body(body)
    return p.build()


# ---------------------------------------------------------------------------
# 2. the mesh recipe (shard_map + ppermute GPipe rotation)
# ---------------------------------------------------------------------------

def make_pipeline_step(mesh: Any, stage_fn: Callable, nstages: int,
                       nmicro: int) -> Callable:
    """Compile a forward pipeline over the ``pp`` mesh axis.

    ``stage_fn(w, x) -> x`` is one stage's computation; weights ``w`` carry
    a leading per-stage axis sharded over ``pp``, microbatches ``xs`` have
    shape ``[nmicro, ...]`` (replicated).  Returns ``run(w, xs) -> ys`` —
    one jitted XLA program executing the GPipe schedule:
    ``nmicro + nstages - 1`` ticks, each a local stage apply plus a
    ``ppermute`` shifting activations one stage forward over ICI.
    """
    import jax
    import jax.numpy as jnp
    from ._compat import pcast, shard_map
    from jax.sharding import PartitionSpec as P

    # no wraparound pair: the last stage's activation retires into ys, and
    # stage 0 always injects fresh microbatches (ppermute zero-fills the
    # unsourced device, which stage 0 never reads)
    right = [(i, i + 1) for i in range(nstages - 1)]
    if nstages != mesh.shape["pp"]:
        raise ValueError(f"nstages={nstages} != pp axis "
                         f"size {mesh.shape['pp']}")

    def spmd(w, xs):
        # w: [1, ...] this stage's weights; xs: [nmicro, ...] replicated
        if xs.shape[0] != nmicro:
            raise ValueError(f"xs carries {xs.shape[0]} microbatches, "
                             f"expected nmicro={nmicro}")
        s = jax.lax.axis_index("pp")
        wl = jax.tree_util.tree_map(lambda a: a[0], w)
        T = nmicro + nstages - 1
        # the carry varies per stage: mark it device-varying up front so the
        # scan carry type is stable (shard_map's vma typing)
        cur0 = pcast(jnp.zeros_like(xs[0]), "pp", to="varying")
        ys0 = pcast(jnp.zeros_like(xs), "pp", to="varying")

        def tick(carry, t):
            cur, ys = carry
            # stage 0 injects microbatch t (while they last); others take
            # the activation handed over by their predecessor last tick
            inject = jnp.where(t < nmicro, t, 0)
            inp = jnp.where(s == 0, xs[inject], cur)
            out = stage_fn(wl, inp)
            # the last stage retires microbatch t-(nstages-1) into ys
            done = t - (nstages - 1)
            keep = (s == nstages - 1) & (done >= 0)
            ys = jnp.where(
                keep,
                jax.lax.dynamic_update_index_in_dim(
                    ys, out, jnp.maximum(done, 0), 0),
                ys)
            nxt = jax.lax.ppermute(out, "pp", right)
            return (nxt, ys), None

        (cur, ys), _ = jax.lax.scan(tick, (cur0, ys0), jnp.arange(T))
        # ys lives on the last stage; share it along pp (psum of one-hot)
        ys = jax.lax.psum(jnp.where(s == nstages - 1, ys, 0.0), "pp")
        return ys

    run = shard_map(spmd, mesh=mesh, in_specs=(P("pp"), P()),
                    out_specs=P())
    return jax.jit(run)
