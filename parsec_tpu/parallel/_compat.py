"""jax API compatibility seams for the parallelism packs.

``shard_map`` graduated from ``jax.experimental`` to the top-level
namespace in jax 0.5 and its replication-check kwarg was renamed
``check_rep`` → ``check_vma``; ``lax.pcast`` exists only under the new
varying-manual-axes typing.  The container floor is jax 0.4.x, so one
guarded seam here keeps the four SPMD modules on a single source of
truth: modern jax passes straight through, 0.4.x gets the kwarg
translated and an identity ``pcast`` (without vma typing there is no
carry type to stabilize).
"""

try:                                    # jax >= 0.5
    from jax import shard_map
except ImportError:                     # jax 0.4.x: still experimental
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_exp(f, **kw)

try:                                    # jax >= 0.6 vma typing
    from jax.lax import pcast
except ImportError:
    def pcast(x, axis_name, *, to):
        return x

__all__ = ["shard_map", "pcast"]
