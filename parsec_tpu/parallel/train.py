"""Composed distributed training step — the parallelism-pack showcase.

SURVEY §2.12 requires DP/TP/PP/SP/EP to be first-class derived schedules.
This module provides the *compiled* (SPMD) realization: a training step
jitted over a ``jax.sharding.Mesh`` via ``shard_map``, with XLA collectives
riding ICI.  The dynamic-runtime realization of the same patterns (halo/ring
PTG taskpools) lives beside it in this package.

Current step: data-parallel batch sharding (``dp``) × megatron-style tensor
parallelism (``tp``: column-sharded W1, row-sharded W2, one ``psum`` per
block).  The sequence-parallel ring-attention and pipeline/expert stages are
layered onto the same mesh as they land in this package.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map


def init_params(key: Any, d_model: int, d_ff: int) -> dict:
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (d_model, d_ff), jnp.float32) * 0.02,
        "w2": jax.random.normal(k2, (d_ff, d_model), jnp.float32) * 0.02,
    }


def make_train_step(mesh: Mesh, lr: float = 0.1):
    """One SGD step of a TP-sharded MLP block over dp×tp."""
    param_specs = {"w1": P(None, "tp"), "w2": P("tp", None)}

    def local_loss(params: dict, x, y):
        h = jax.nn.relu(x @ params["w1"])        # [b, s, d_ff/tp]
        o = lax.psum(h @ params["w2"], "tp")     # row-parallel matmul reduce
        return jnp.mean((o - y) ** 2)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, P("dp"), P("dp")),
        out_specs=(param_specs, P()),
        check_rep=False,
    )
    def step(params: dict, x, y):
        loss, grads = jax.value_and_grad(local_loss)(params, x, y)
        # data-parallel gradient reduction over dp (tp shards stay sharded)
        grads = jax.tree.map(lambda g: lax.pmean(g, "dp"), grads)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, lax.pmean(loss, "dp")

    return jax.jit(step)
