"""Composed distributed training steps — the parallelism-pack showcase.

SURVEY §2.12 requires DP/TP/PP/SP/EP to be first-class derived schedules.
This module provides the *compiled* (SPMD) realization: training steps
jitted over a ``jax.sharding.Mesh`` via ``shard_map``, with XLA collectives
riding ICI.  The dynamic-runtime realization of the same patterns (halo/ring
PTG taskpools, redistribute) lives beside it in this package.

Two steps:

- :func:`make_train_step` — dp × tp MLP block (megatron-style column/row
  sharding, one ``psum`` per block);
- :func:`make_transformer_train_step` — the flagship dp × tp × sp step: a
  transformer block whose attention is **ring attention** over the ``sp``
  axis (:mod:`parsec_tpu.parallel.ring`), heads sharded over ``tp``, batch
  over ``dp``; gradients for replicated params reduce over dp × sp.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ._compat import shard_map

from .ring import ring_attention_local


def psum_r(x, axis_name: str):
    """Megatron's *g* operator: forward allreduce, backward identity.

    Placed AFTER a row-parallel matmul.  Inside ``shard_map(...,
    check_vma=False)`` the transpose of ``lax.psum`` is another ``psum`` —
    but the cotangent arriving here is replicated (the loss is computed
    identically on every shard of ``axis_name``), so the correct backward
    is the identity, not another allreduce.
    """
    @jax.custom_vjp
    def f(v):
        return lax.psum(v, axis_name)

    f.defvjp(lambda v: (lax.psum(v, axis_name), None),
             lambda _, g: (g,))
    return f(x)


def ident_f(x, axis_name: str):
    """Megatron's *f* operator: forward identity, backward allreduce.

    Placed BEFORE a column-parallel matmul on a replicated activation: each
    shard back-propagates only its own head-group/column contribution into
    the activation, so the true cotangent is the psum of the per-shard
    partials.  Omitting this leaves activation gradients tp-local and the
    upstream parameter gradients silently wrong.
    """
    @jax.custom_vjp
    def f(v):
        return v

    f.defvjp(lambda v: (v, None),
             lambda _, g: (lax.psum(g, axis_name),))
    return f(x)


def init_params(key: Any, d_model: int, d_ff: int) -> dict:
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (d_model, d_ff), jnp.float32) * 0.02,
        "w2": jax.random.normal(k2, (d_ff, d_model), jnp.float32) * 0.02,
    }


def make_train_step(mesh: Mesh, lr: float = 0.1):
    """One SGD step of a TP-sharded MLP block over dp×tp."""
    param_specs = {"w1": P(None, "tp"), "w2": P("tp", None)}

    def local_loss(params: dict, x, y):
        h = jax.nn.relu(x @ params["w1"])        # [b, s, d_ff/tp]
        o = psum_r(h @ params["w2"], "tp")       # row-parallel matmul reduce
        return jnp.mean((o - y) ** 2)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, P("dp"), P("dp")),
        out_specs=(param_specs, P()),
        check_vma=False,
    )
    def step(params: dict, x, y):
        loss, grads = jax.value_and_grad(local_loss)(params, x, y)
        # data-parallel gradient reduction over dp (tp shards stay sharded)
        grads = jax.tree.map(lambda g: lax.pmean(g, "dp"), grads)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, lax.pmean(loss, "dp")

    return jax.jit(step)


# ---------------------------------------------------------------------------
# flagship: transformer block over dp × tp × sp
# ---------------------------------------------------------------------------

def init_transformer_params(key: Any, d_model: int, n_heads: int,
                            d_head: int, d_ff: int) -> dict:
    ks = jax.random.split(key, 6)
    s = 0.02
    return {
        "wq": jax.random.normal(ks[0], (d_model, n_heads * d_head)) * s,
        "wk": jax.random.normal(ks[1], (d_model, n_heads * d_head)) * s,
        "wv": jax.random.normal(ks[2], (d_model, n_heads * d_head)) * s,
        "wo": jax.random.normal(ks[3], (n_heads * d_head, d_model)) * s,
        "w1": jax.random.normal(ks[4], (d_model, d_ff)) * s,
        "w2": jax.random.normal(ks[5], (d_ff, d_model)) * s,
    }


def transformer_param_specs() -> dict:
    """qkv projections column-sharded by head group (tp); wo row-sharded;
    MLP megatron-style.  Replicated across dp and sp."""
    return {
        "wq": P(None, "tp"), "wk": P(None, "tp"), "wv": P(None, "tp"),
        "wo": P("tp", None),
        "w1": P(None, "tp"), "w2": P("tp", None),
    }


def make_transformer_train_step(mesh: Mesh, n_heads: int, d_head: int,
                                lr: float = 0.1, causal: bool = True):
    """One SGD step of a transformer block: ring attention over ``sp``,
    head-group tensor parallelism over ``tp``, batch over ``dp``."""
    param_specs = transformer_param_specs()
    tp_size = mesh.shape["tp"]
    h_loc = n_heads // tp_size
    assert h_loc * tp_size == n_heads, (n_heads, tp_size)

    def block(params: dict, x):
        # x: [b_l, s_l, d]; projections are tp-local head groups
        b, s, d = x.shape

        def heads(t):   # [b_l, s_l, h_l*dh] -> [b_l, h_l, s_l, dh]
            return t.reshape(b, s, h_loc, d_head).transpose(0, 2, 1, 3)

        # Megatron f/g pairing: ident_f before the column-parallel
        # projections (backward psums the per-head-group activation
        # cotangents), psum_r after the row-parallel ones
        xf = ident_f(x, "tp")
        q = heads(xf @ params["wq"])
        k = heads(xf @ params["wk"])
        v = heads(xf @ params["wv"])
        a = ring_attention_local(q, k, v, axis_name="sp", causal=causal)
        a = a.transpose(0, 2, 1, 3).reshape(b, s, h_loc * d_head)
        x = x + psum_r(a @ params["wo"], "tp")
        h = jax.nn.relu(ident_f(x, "tp") @ params["w1"])
        x = x + psum_r(h @ params["w2"], "tp")
        return x

    def local_loss(params: dict, x, y):
        o = block(params, x)
        return jnp.mean((o - y) ** 2)

    data_spec = P("dp", "sp", None)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, data_spec, data_spec),
        out_specs=(param_specs, P()),
        check_vma=False,
    )
    def step(params: dict, x, y):
        loss, grads = jax.value_and_grad(local_loss)(params, x, y)
        # params replicate across dp and sp: reduce their grads over both
        grads = jax.tree.map(
            lambda g: lax.pmean(lax.pmean(g, "dp"), "sp"), grads)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, lax.pmean(lax.pmean(loss, "dp"), "sp")

    return jax.jit(step)
