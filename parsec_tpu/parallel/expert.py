"""Expert parallelism (EP) on the dataflow core and on the mesh.

SURVEY §2.12's missing EP recipe: a mixture-of-experts feed-forward whose
*experts* are placed by an arbitrary tile→rank table — the
:class:`~parsec_tpu.data_dist.matrix.TwoDimTabular` distribution
(``/root/reference/parsec/data_dist/matrix/two_dim_tabular.c``), exactly the
substrate the reference provides for irregular placements.

Static-capacity top-1 routing as a three-class PTG (:func:`moe_ptg`):

- ``GATE(b)`` on the rank owning token block ``b``: computes the router
  argmax and packs, for every expert ``e``, a fixed-capacity buffer
  ``[cap, 1+d]`` — column 0 the originating token row (``-1`` pads),
  columns 1: the token values.  One guarded output dep per ``(b, e)`` pair
  forms the static all-to-all, each buffer shipping to wherever the table
  put its expert.
- ``EXPERT(e)`` on ``rank_table(e)``: applies its FFN to the value columns
  of every incoming buffer; the index column rides along.
- ``COMBINE(b)`` back on ``b``'s rank: scatters expert outputs to their
  original rows by the carried indices and writes the result tile.

The routing *decision* is data (the index column), never graph structure —
all shapes and edges are static, which is what keeps the recipe lowerable
and TPU-friendly.

The mesh-side incarnation (:func:`make_moe_step`) is the standard dense
one-hot dispatch/combine einsum pair over an ``ep`` mesh axis: experts
shard, GSPMD turns the dispatch contraction into the all-to-all.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import ptg
from ..data_dist.matrix import TwoDimTabular

__all__ = ["moe_ptg", "reference_moe", "make_moe_step"]


# ---------------------------------------------------------------------------
# routing kernels (CPU bodies; pure, reused by the tests)
# ---------------------------------------------------------------------------

def _gate_pack(x: np.ndarray, wg: np.ndarray, nexperts: int,
               cap: int) -> list[np.ndarray]:
    """Top-1 route: per-expert ``[cap, 1+d]`` packed buffers."""
    d = x.shape[1]
    sel = np.argmax(x @ wg, axis=1)
    out = []
    for e in range(nexperts):
        buf = np.full((cap, 1 + d), -1.0, dtype=np.float32)
        rows = np.flatnonzero(sel == e)[:cap]
        buf[:len(rows), 0] = rows.astype(np.float32)
        buf[:len(rows), 1:] = x[rows]
        out.append(buf)
    return out


def _expert_apply(buf: np.ndarray, w: np.ndarray) -> np.ndarray:
    """FFN on the value columns; the index column rides along."""
    out = np.array(buf, dtype=np.float32)
    valid = out[:, 0] >= 0
    h = np.maximum(out[:, 1:] @ w, 0.0)          # relu(x @ W_e)
    out[:, 1:] = np.where(valid[:, None], h, out[:, 1:])
    return out


def reference_moe(x: np.ndarray, wg: np.ndarray,
                  we: np.ndarray) -> np.ndarray:
    """Dense reference: top-1 routed relu(x @ W_sel) per token."""
    sel = np.argmax(x @ wg, axis=1)
    y = np.zeros_like(x, dtype=np.float32)
    for i, e in enumerate(sel):
        y[i] = np.maximum(x[i] @ we[e], 0.0)
    return y


# ---------------------------------------------------------------------------
# the dataflow-core recipe
# ---------------------------------------------------------------------------

def moe_ptg(X: Any, W: TwoDimTabular, wg: np.ndarray, nexperts: int,
            name: str = "moe") -> "ptg.PTGTaskpool":
    """Build the EP PTG.

    ``X``: token-block collection — ``X(b, 0)`` is a ``[ntok, d]`` tile;
    outputs overwrite it.  ``W``: expert weights, one tile per expert —
    ``W.rank_of(e, 0)`` IS the expert placement.  ``wg``: the replicated
    ``[d, nexperts]`` router matrix.

    Flow-name convention (the static all-to-all): GATE's buffer flow
    ``B<e>`` targets EXPERT's ``X<b>`` — the target flow name depends on
    the *source* task's local, so each (b, e) pair gets its own guarded
    dep (``guard: l.b == b``); exactly one is active per instance.
    """
    B, E = X.mt, nexperts
    cap = X.mb   # full capacity: top-1, no token dropping

    p = ptg.PTGBuilder(name, X=X, W=W, WG=np.asarray(wg, np.float32),
                       B=B, E=E, CAP=cap)

    # ---- GATE(b) ----------------------------------------------------------
    ga = p.task("GATE", b=ptg.span(0, lambda g, l: g.B - 1))
    ga.affinity("X", lambda g, l: (l.b, 0))
    ga.flow("T", ptg.READ).input(data=("X", lambda g, l: (l.b, 0)))
    for e in range(E):
        fb = ga.flow(f"B{e}", ptg.WRITE)
        for b in range(B):
            fb.output(succ=("EXPERT", f"X{b}",
                            lambda g, l, e=e: {"e": e}),
                      guard=lambda g, l, b=b: l.b == b)

    def gate_body(es, task, g, l):
        from ..data.data import data_create
        x = np.asarray(task.flow_data("T").value, dtype=np.float32)
        packed = _gate_pack(x, g.WG, g.E, g.CAP)
        for e in range(g.E):
            task.set_flow_data(
                f"B{e}", data_create(
                    packed[e],
                    key=(task.taskpool.name, "g", l.b, e)).get_copy(0))

    ga.body(gate_body)

    # ---- EXPERT(e) --------------------------------------------------------
    ex = p.task("EXPERT", e=ptg.span(0, lambda g, l: g.E - 1))
    ex.affinity("W", lambda g, l: (l.e, 0))
    ex.flow("WF", ptg.READ).input(data=("W", lambda g, l: (l.e, 0)))
    for b in range(B):
        fx = ex.flow(f"X{b}", ptg.RW)
        for e in range(E):
            fx.input(pred=("GATE", f"B{e}",
                           lambda g, l, b=b: {"b": b}),
                     guard=lambda g, l, e=e: l.e == e)
        for e in range(E):
            fx.output(succ=("COMBINE", f"R{e}",
                            lambda g, l, b=b: {"b": b}),
                      guard=lambda g, l, e=e: l.e == e)

    def expert_body(es, task, g, l):
        w = np.asarray(task.flow_data("WF").value, dtype=np.float32)
        for b in range(g.B):
            buf = task.flow_data(f"X{b}")
            buf.value = _expert_apply(np.asarray(buf.value), w)
            buf.version += 1

    ex.body(expert_body)

    # ---- COMBINE(b) -------------------------------------------------------
    co = p.task("COMBINE", b=ptg.span(0, lambda g, l: g.B - 1))
    co.affinity("X", lambda g, l: (l.b, 0))
    cy = co.flow("Y", ptg.RW)
    cy.input(data=("X", lambda g, l: (l.b, 0)))
    cy.output(data=("X", lambda g, l: (l.b, 0)))
    for e in range(E):
        fr = co.flow(f"R{e}", ptg.READ)
        for b in range(B):
            fr.input(pred=("EXPERT", f"X{b}",
                           lambda g, l, e=e: {"e": e}),
                     guard=lambda g, l, b=b: l.b == b)

    def combine_body(es, task, g, l):
        y = task.flow_data("Y")
        out = np.zeros_like(np.asarray(y.value), dtype=np.float32)
        for e in range(g.E):
            buf = np.asarray(task.flow_data(f"R{e}").value)
            valid = buf[:, 0] >= 0
            rows = buf[valid, 0].astype(np.int64)
            out[rows] = buf[valid, 1:]
        y.value = out
        y.version += 1

    co.body(combine_body)
    return p.build()


# ---------------------------------------------------------------------------
# the mesh recipe (dense dispatch einsums over an "ep" axis)
# ---------------------------------------------------------------------------

def make_moe_step(mesh: Any) -> Any:
    """Compile the dense-dispatch MoE step over an ``ep`` mesh axis.

    ``step(x, wg, we)``: tokens ``[T, d]`` (replicated), router ``[d, E]``
    (replicated), expert weights ``[E, d, d]`` sharded over ``ep``.  The
    one-hot dispatch/combine einsums are what GSPMD lowers to the
    all-to-all — the standard TPU MoE pattern.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def step(x, wg, we):
        sel = jnp.argmax(x @ wg, axis=-1)                   # [T]
        onehot = jax.nn.one_hot(sel, we.shape[0],
                                dtype=x.dtype)              # [T, E]
        xe = jnp.einsum("te,td->etd", onehot, x)            # dispatch
        he = jax.nn.relu(jnp.einsum("etd,edf->etf", xe, we))
        return jnp.einsum("te,etf->tf", onehot, he)         # combine

    repl = NamedSharding(mesh, P())
    shard_e = NamedSharding(mesh, P("ep"))
    return jax.jit(step, in_shardings=(repl, repl, shard_e),
                   out_shardings=repl)
