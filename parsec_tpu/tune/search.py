"""Budgeted knob search: coordinate descent with random restarts
(ISSUE 18).

One trial = run the workload under a scoped MCA override
(``params.overrides``) of a candidate knob vector and score it.  The
search walks the DECLARED knob space (``core/params.KnobSpec`` — the
search can only move knobs their owning modules declared tunable),
coordinate by coordinate, keeping improving moves; when a full sweep
makes no move it random-restarts from a sampled vector until the trial
budget is spent.

The perf ledger (``prof/perfdb.py``) is both provenance and memory:
every executed trial is appended under the ``tune.<signature>``
workload with its full knob vector in the key, so the EWMA sentinel's
history seeds later searches — a candidate whose recorded history is
already far worse than the incumbent is pruned without spending a
trial.  The winning vector persists to the tuning DB
(``tune/db.py``) under the workload's structural signature (and,
optionally, an ambient tag a fresh Context / per-tenant submit
consults).
"""

from __future__ import annotations

import math
import random
import time
from typing import Any, Callable

from ..core.params import KnobSpec, params as _params
from ..prof import perfdb as _perfdb
from .db import TuneDB
from .signature import ambient_signature

# a candidate whose perfdb EWMA is this factor worse than the incumbent
# score is pruned from the search without re-measuring
PRUNE_FACTOR = 2.0


def declared_space(names: list[str] | None = None) -> dict[str, KnobSpec]:
    """The search domain: the declared knob space, optionally
    restricted to ``names`` (undeclared names raise — an undeclared
    param is configuration, not a knob)."""
    space = _params.knob_space()
    if names is None:
        return space
    missing = [n for n in names if n not in space]
    if missing:
        raise KeyError(f"undeclared knob(s): {missing} "
                       f"(declare via params.declare_knob)")
    return {n: space[n] for n in names}


def score_from_report(objective: str) -> float | None:
    """Pull ``objective`` out of the runtime self-measurement: a flat
    ``runtime_report()`` scalar, or an SLO quantile spelled
    ``slo:<metric>_p<q>`` (e.g. ``slo:tok_latency_ms_p99``) from the
    merged per-tenant plane.  ``None`` when the run recorded nothing."""
    from ..prof.flight_recorder import runtime_report
    if objective.startswith("slo:"):
        # "slo:tok_latency_ms_p99" — the worst tenant's value across
        # every live plane (the summary is {tenant: {metric_pQ: v}})
        name = objective[4:]
        _metric, _, q = name.rpartition("_p")
        try:
            from ..prof.histogram import merged_summary
            s = merged_summary(quantiles=(float(q) / 100.0,))
        except Exception:               # noqa: BLE001 — no plane, no score
            return None
        vals = [d[name] for d in s.values()
                if isinstance(d, dict)
                and isinstance(d.get(name), (int, float))]
        return float(max(vals)) if vals else None
    v = runtime_report().get(objective)
    return float(v) if isinstance(v, (int, float)) else None


class _Evaluator:
    """Runs + scores one knob vector, with perfdb provenance/pruning."""

    def __init__(self, workload_fn: Callable[[dict], Any], signature: str,
                 objective: str, perf: "_perfdb.PerfDB | None",
                 note: Callable[..., None] | None) -> None:
        self.fn = workload_fn
        self.signature = signature
        self.objective = objective
        self.perf = perf
        self.note = note
        self.higher = _perfdb.better_of(objective) == "higher"
        self.evals = 0
        self.pruned = 0
        self.trials: list[dict] = []
        self._seen: dict[tuple, float] = {}

    def _key(self, knobs: dict) -> str:
        return _perfdb.make_key(f"tune.{self.signature}", self.objective,
                                knobs=knobs)

    def better(self, a: float, b: float) -> bool:
        return a > b if self.higher else a < b

    def prior(self, knobs: dict) -> float | None:
        """The perfdb EWMA of this exact vector's history, if any."""
        if self.perf is None:
            return None
        hist = self.perf.history(self._key(knobs))
        if not hist:
            return None
        m, _sd, _n = self.perf._ewma(hist)
        return m

    def __call__(self, knobs: dict, incumbent: float | None) -> float | None:
        """Score ``knobs`` (memoized); ``None`` = pruned or failed."""
        frozen = tuple(sorted(knobs.items()))
        if frozen in self._seen:
            return self._seen[frozen]
        prior = self.prior(knobs)
        if prior is not None and incumbent is not None:
            bad = (prior < incumbent / PRUNE_FACTOR if self.higher
                   else prior > incumbent * PRUNE_FACTOR)
            if bad:
                self.pruned += 1
                self._seen[frozen] = prior      # known-bad: trust history
                return prior
        mca = {n: v for n, v in knobs.items()
               if _params.knob_spec(n) is not None
               and self._registered(n)}
        t0 = time.perf_counter()
        try:
            with _params.overrides(mca):
                out = self.fn(dict(knobs))
        except Exception:               # noqa: BLE001 — a failed trial is
            self._seen[frozen] = math.inf if not self.higher else -math.inf
            return None                 # just a non-move, never fatal
        wall = time.perf_counter() - t0
        if isinstance(out, dict):
            score = out.get(self.objective)
        elif isinstance(out, (int, float)) and not isinstance(out, bool):
            score = float(out)
        else:
            score = None
        if score is None:
            score = (score_from_report(self.objective)
                     if self.objective != "wall_s" else None)
        if score is None:
            score = wall                # the universal fallback objective
        score = float(score)
        self.evals += 1
        self._seen[frozen] = score
        self.trials.append({"knobs": dict(knobs), "score": score,
                            "wall_s": round(wall, 4)})
        if self.perf is not None:
            try:
                self.perf.note_trial(f"tune.{self.signature}",
                                     self.objective, score, knobs=knobs,
                                     meta={"trial": self.evals})
            except Exception:           # noqa: BLE001 — ledger never fatal
                pass
        if self.note is not None:
            try:
                self.note(trial=self.evals, score=score, knobs=dict(knobs))
            except Exception:           # noqa: BLE001 — observer never fatal
                pass
        return score

    @staticmethod
    def _registered(name: str) -> bool:
        try:
            _params.get(name)
            return True
        except KeyError:
            return False


def search(workload_fn: Callable[[dict], Any], *, signature: str,
           space: dict[str, KnobSpec] | None = None, budget: int = 16,
           restarts: int = 1, objective: str = "wall_s", seed: int = 0,
           start: dict | None = None, db: TuneDB | None = None,
           persist: bool = True, ambient_tag: str | None = None,
           note: Callable[..., None] | None = None) -> dict:
    """Coordinate-descent search over ``space`` (default: every
    declared knob), at most ``budget`` executed trials.

    ``workload_fn(knobs)`` runs the workload under the already-applied
    scoped MCA overrides (knobs without a registered param — e.g. a
    workload-level tile size — are the callable's to consume) and
    returns the score: a number, a dict carrying ``objective``, or
    ``None`` to fall back to measured wall seconds /
    :func:`score_from_report`.

    Returns ``{"best", "best_score", "evals", "pruned", "trials"}``;
    with ``persist`` the winner lands in the tuning DB under
    ``signature`` (and ``ambient:<ambient_tag>`` when given), where
    ``Context`` start / per-tenant submit pick it up."""
    space = dict(space if space is not None else _params.knob_space())
    if not space:
        raise ValueError("empty knob space: declare knobs first")
    db = db or TuneDB()
    ev = _Evaluator(workload_fn, signature, objective,
                    _perfdb.PerfDB() if _params.get("perfdb") else None,
                    note)
    rng = random.Random(seed)

    def start_vector(r: int) -> dict:
        if r > 0:
            return {n: spec.sample(rng) for n, spec in space.items()}
        # restart 0: current values, then a persisted earlier winner,
        # then the caller's explicit start vector — most specific wins
        vec = {n: _params.get(n) if ev._registered(n) else spec.sample(rng)
               for n, spec in space.items()}
        prev = db.best(signature, objective=objective)
        if prev is not None:
            for n, v in prev["knobs"].items():
                if n in space and space[n].contains(v):
                    vec[n] = v
        if start is not None:
            vec.update({n: v for n, v in start.items() if n in space})
        return vec

    best_vec: dict | None = None
    best_score: float | None = None
    for r in range(max(1, restarts)):
        if ev.evals >= budget:
            break
        cur = start_vector(r)
        cur_score = ev(cur, best_score)
        if cur_score is None:
            continue
        if best_score is None or ev.better(cur_score, best_score):
            best_vec, best_score = dict(cur), cur_score
        moved = True
        while moved and ev.evals < budget:
            moved = False
            for name, spec in space.items():
                if ev.evals >= budget:
                    break
                for cand in spec.neighbors(cur[name]):
                    if ev.evals >= budget:
                        break
                    trial = dict(cur)
                    trial[name] = cand
                    s = ev(trial, best_score)
                    if s is not None and ev.better(s, cur_score):
                        cur, cur_score = trial, s
                        moved = True
                        if ev.better(s, best_score):
                            best_vec, best_score = dict(trial), s
    out = {"best": best_vec, "best_score": best_score,
           "objective": objective, "signature": signature,
           "evals": ev.evals, "pruned": ev.pruned, "trials": ev.trials}
    if persist and best_vec is not None and best_score is not None \
            and math.isfinite(best_score):
        db.note(signature, best_vec, best_score, objective=objective,
                source="search")
        if ambient_tag:
            db.note(ambient_signature(ambient_tag), best_vec, best_score,
                    objective=objective, source="search")
        out["db_path"] = db.path
    return out
