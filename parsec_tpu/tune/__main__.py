"""``python -m parsec_tpu.tune --self-test`` — the scripts/check.sh
gate: the budgeted search must find the basin of a synthetic quadratic
objective within its trial budget, the winner must round-trip through
the tuning DB, and the ambient consult must hand it back filtered to
the declared space."""

from __future__ import annotations

import os
import sys
import tempfile


def self_test() -> int:
    from ..core.params import params
    from . import ambient_signature, apply_ambient
    from .db import TuneDB
    from .search import declared_space, search

    # a private 2-D knob space: one registered numeric knob, one
    # enumerated — the search must navigate both kinds
    params.register("tune_selftest_x", 32,
                    "tune self-test knob (synthetic)")
    params.declare_knob("tune_selftest_x", lo=1, hi=256, scale="log2")
    params.register("tune_selftest_mode", "slow",
                    "tune self-test knob (synthetic)")
    params.declare_knob("tune_selftest_mode", values=("slow", "fast"))
    space = declared_space(["tune_selftest_x", "tune_selftest_mode"])

    calls = {"n": 0}

    def objective(knobs: dict) -> float:
        # the scoped override IS the contract: the workload reads its
        # knobs through the params registry, like any real stage
        calls["n"] += 1
        x = params.get("tune_selftest_x")
        mode = params.get("tune_selftest_mode")
        import math
        return (math.log2(x) - 4.0) ** 2 + (5.0 if mode == "slow" else 0.0)

    with tempfile.TemporaryDirectory(prefix="tunedb_") as d:
        db = TuneDB(os.path.join(d, "tunedb.jsonl"))
        budget = 24
        out = search(objective, signature="selftest:quadratic",
                     space=space, budget=budget, restarts=2,
                     objective="cost_s", seed=7, db=db,
                     ambient_tag="selftest")
        assert out["evals"] <= budget, out
        best = out["best"]
        assert best is not None, out
        # the basin: x=16 (log2=4), mode=fast, score 0
        assert best["tune_selftest_mode"] == "fast", out
        assert 8 <= best["tune_selftest_x"] <= 32, out
        assert out["best_score"] <= 1.0 + 1e-9, out
        # overrides restored after every trial: the live values are
        # untouched defaults
        assert params.get("tune_selftest_x") == 32
        assert params.get("tune_selftest_mode") == "slow"

        # DB round-trip: a FRESH store instance reads the winner back
        db2 = TuneDB(db.path)
        rec = db2.best("selftest:quadratic", objective="cost_s")
        assert rec is not None and rec["knobs"] == best, rec

        # ambient consult + apply: the persisted winner lands on the
        # registered params (filtered to the declared space)
        prev = str(params.get("tune_db_path") or "")
        params.set("tune_db_path", db.path)
        try:
            applied = apply_ambient("selftest")
        finally:
            params.set("tune_db_path", prev)
        assert applied == best, (applied, best)
        assert params.get("tune_selftest_mode") == "fast"
        params.set("tune_selftest_x", 32)       # restore
        params.set("tune_selftest_mode", "slow")
        assert db2.best(ambient_signature("selftest"),
                        objective="cost_s") is not None

    print(f"tune self-test: ok (quadratic basin found in {out['evals']} "
          f"trials of {budget}, {out['pruned']} pruned; DB round-trip + "
          f"ambient apply)")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-test" in argv:
        return self_test()
    print(__doc__, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
