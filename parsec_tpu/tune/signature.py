"""Workload signatures: what a stored knob vector is FOR (ISSUE 18).

Two kinds, both plain strings (the tuning DB's first key column,
``tune/db.py``):

- **structural** (:func:`workload_signature`): derived from the PR-2
  lowering-cache machinery — the task-class table (names, task counts,
  kernel names, flow names), the wavefront shape, store row geometry
  when lowered — plus a power-of-two **size bucket**, digested to a
  short stable hex.  The in-process lowering signature freezes kernels
  by object identity (``lowering._freeze``), which can never agree
  across processes; :func:`parsec_tpu.ptg.lowering.structural_fingerprint`
  re-expresses the same axes by *name*, so two processes lowering the
  same program land on the same signature — the property the
  persistence tests pin.  The backend triple deliberately stays OUT of
  the signature: it is the DB key's second column, so "same structure,
  different backend" is a key miss, not a false hit.

- **ambient** (:func:`ambient_signature`): a tag for vectors applied
  before any workload structure exists — ``ambient:context`` at
  :class:`~parsec_tpu.runtime.context.Context` start,
  ``ambient:tenant:<t>`` at RuntimeServer per-tenant submit.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any


def size_bucket(n: float | int) -> int:
    """Power-of-two bucket of a workload size (task count, matrix n,
    token count...): vectors tuned at n=8192 apply at n=8192+epsilon,
    never at n=64."""
    n = int(n)
    return 0 if n <= 1 else n.bit_length() - 1


def fingerprint(obj) -> dict:
    """The structural fingerprint dict (see
    :func:`parsec_tpu.ptg.lowering.structural_fingerprint`) — exposed
    here so signature consumers need not import the lowering module."""
    from ..ptg.lowering import structural_fingerprint
    return structural_fingerprint(obj)


def workload_signature(obj: Any, size_hint: float | None = None) -> str:
    """Structural signature of a Taskpool / LoweredTaskpool.

    ``size_hint`` overrides the bucketed size axis (default: the
    fingerprint's total task count) — callers whose task count hides
    the real scale (one decode pool per iteration, say) pass tokens or
    matrix n instead."""
    fp = fingerprint(obj)
    bucket = size_bucket(size_hint if size_hint is not None
                         else fp.get("ntasks", 0))
    blob = json.dumps({"fp": fp, "bucket": bucket}, sort_keys=True,
                      separators=(",", ":")).encode()
    digest = hashlib.blake2b(blob, digest_size=10).hexdigest()
    # a human-scannable prefix (first class name) + the discriminating
    # digest: `--history`-style tooling stays readable
    head = fp["classes"][0][0] if fp.get("classes") else "empty"
    return f"wl:{head}:b{bucket}:{digest}"


def ambient_signature(tag: str) -> str:
    return f"ambient:{tag}"
