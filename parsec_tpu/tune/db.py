"""Persistent tuning DB (ISSUE 18): the knob vectors that won.

An append-only JSONL store (``$PARSEC_TPU_ARTIFACT_DIR/tunedb.jsonl``
by default, the perf ledger's sibling) keyed by ``(signature, backend,
objective)``:

- **signature** — a workload's structural signature
  (:mod:`parsec_tpu.tune.signature`: lowering class table + wavefront
  shape + size bucket, digested) or an *ambient* tag
  (``ambient:context``, ``ambient:tenant:<t>``) for vectors applied
  before any workload structure exists;
- **backend** — the lowering cache's ``(jax version, backend, device
  kind)`` triple: a vector tuned on TPU never applies on CPU;
- **objective** — what the score means (``wall_s``, ``tok_p99_ms``...),
  with direction from :func:`parsec_tpu.prof.perfdb.better_of`.

``best(signature)`` answers "what knob vector should this run use" in
one in-memory dict probe: the file is parsed once per (mtime, size)
generation and indexed, so the Context-start / per-tenant-submit
consults stay far under the perf_smoke 50µs lookup gate.  Writers only
ever append; the best-per-key reduction happens at read time, so
concurrent tuners and adapters can share one file without coordination
(the perfdb torn-tail discipline applies: a half-written last line is
skipped, never fatal).

MCA knobs: ``tune_db`` (0 disables every consult), ``tune_db_path``
(overrides the store location).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time

from ..core.params import params as _params
from ..prof.perfdb import backend_signature, better_of

_params.register("tune_db", True,
                 "consult the persistent tuning DB at Context start and "
                 "RuntimeServer per-tenant submit and apply the stored "
                 "knob vector (0 = always run at the configured "
                 "defaults; explicit env/cli overrides always win)")
_params.register("tune_db_path", "",
                 "tuning DB location (default: "
                 "$PARSEC_TPU_ARTIFACT_DIR/tunedb.jsonl, else "
                 "/tmp/tunedb.jsonl)")

# concurrency contracts (analysis.runtimelint, docs/ANALYSIS.md): the
# process-wide parsed-generation cache mutates only under _cache_lock
# (Context start and per-tenant submit probe it concurrently; declared
# here as the module contract — the cache is a module global, so the
# subscript sites are documentation, the `with _cache_lock` discipline
# in cached_db is the enforcement).  TuneDB instances themselves are
# intentionally NOT declared: a DB is single-owner (each cached
# generation is parsed once before publication, then read-only; writers
# append to their own handle), so adding a lock would tax the sub-50µs
# consult path for a race that cannot occur.
_LOCK_PROTECTED = {
    "db._cached": "_cache_lock",
}
_LOCK_ORDER = ("_cache_lock",)


def default_path() -> str:
    p = str(_params.get("tune_db_path") or "")
    if p:
        return p
    return os.path.join(os.environ.get("PARSEC_TPU_ARTIFACT_DIR", "/tmp"),
                        "tunedb.jsonl")


def make_key(signature: str, backend: list | None = None,
             objective: str = "wall_s") -> str:
    """Canonical key string — same discipline as
    :func:`parsec_tpu.prof.perfdb.make_key`: equal key ⇒ the stored
    vector is applicable (same structure, same backend, same meaning of
    the score)."""
    return json.dumps({"sig": signature,
                       "backend": backend if backend is not None
                       else backend_signature(),
                       "objective": objective},
                      sort_keys=True, separators=(",", ":"))


class TuneDB:
    """One tuning store file: ``note`` appends a scored knob vector,
    ``best`` returns the winning vector for a key (direction from the
    objective name), ``None`` on miss."""

    def __init__(self, path: str | None = None) -> None:
        self.path = path or default_path()
        self._records: list[dict] | None = None
        self._best: dict[str, dict] | None = None

    # -- storage ---------------------------------------------------------
    def records(self) -> list[dict]:
        if self._records is not None:
            return self._records
        recs: list[dict] = []
        try:
            with open(self.path) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        r = json.loads(ln)
                    except ValueError:
                        continue        # torn tail line: skip, keep rest
                    if isinstance(r, dict) and isinstance(
                            r.get("knobs"), dict):
                        recs.append(r)
        except OSError:
            pass
        self._records = recs
        return recs

    def note(self, signature: str, knobs: dict, score: float, *,
             objective: str = "wall_s", backend: list | None = None,
             source: str = "search", meta: dict | None = None) -> dict:
        """Append one scored vector.  ``source`` says who produced it
        (``search`` / ``adaptive`` / ``seed``) — provenance, not part of
        the key."""
        if not math.isfinite(float(score)):
            raise ValueError(f"non-finite tune score: {score!r}")
        rec = {"key": make_key(signature, backend, objective),
               "sig": signature, "objective": objective,
               "knobs": dict(knobs), "score": float(score),
               "source": source, "ts": round(time.time(), 3)}
        if meta:
            rec["meta"] = meta
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True,
                               separators=(",", ":")) + "\n")
        if self._records is not None:
            self._records.append(rec)
        self._best = None
        return rec

    # -- queries ---------------------------------------------------------
    def _index(self) -> dict[str, dict]:
        if self._best is not None:
            return self._best
        best: dict[str, dict] = {}
        for r in self.records():
            k = r.get("key")
            s = r.get("score")
            if not isinstance(k, str) or not isinstance(s, (int, float)):
                continue
            cur = best.get(k)
            if cur is None:
                best[k] = r
                continue
            hi = better_of(str(r.get("objective", ""))) == "higher"
            if (s > cur["score"]) == hi and s != cur["score"]:
                best[k] = r
        self._best = best
        return best

    def best(self, signature: str, *, objective: str = "wall_s",
             backend: list | None = None) -> dict | None:
        """The winning record for ``(signature, backend, objective)`` —
        ``{"knobs": ..., "score": ..., "source": ...}`` — or ``None``:
        the caller falls back to its configured defaults."""
        return self._index().get(make_key(signature, backend, objective))


# -- the process-wide cached consult path -----------------------------------
# Context start and per-tenant submit probe the DB on hot paths; the
# file is re-parsed only when its (mtime_ns, size) generation moves.
_cache_lock = threading.Lock()
_cached: dict[str, tuple[tuple, TuneDB]] = {}


def cached_db(path: str | None = None) -> TuneDB:
    path = path or default_path()
    try:
        st = os.stat(path)
        gen = (st.st_mtime_ns, st.st_size)
    except OSError:
        gen = (0, -1)                   # absent file: one shared miss DB
    with _cache_lock:
        hit = _cached.get(path)
        if hit is not None and hit[0] == gen:
            return hit[1]
        db = TuneDB(path)
        _cached[path] = (gen, db)
        return db


def best(signature: str, *, objective: str = "wall_s",
         backend: list | None = None, path: str | None = None
         ) -> dict | None:
    """Module-level convenience over the cached store."""
    return cached_db(path).best(signature, objective=objective,
                                backend=backend)
