"""Closed-loop autotuner (ISSUE 18): signature-keyed knob search over
the perf ledger, with live per-tenant adaptation.

Three parts (docs/TUNING.md):

- :mod:`~parsec_tpu.tune.signature` + :mod:`~parsec_tpu.tune.search` —
  a workload's structural signature (derived from the PR-2 lowering
  machinery) keys a budgeted coordinate-descent search over the
  DECLARED knob space (``core/params.KnobSpec``), each trial running
  under a scoped MCA override and recorded to the perf ledger;
- :mod:`~parsec_tpu.tune.db` — the persistent tuning DB
  (``tunedb.jsonl``) consulted at ``Context`` start and per-tenant
  submit (``tune_db=1``);
- :mod:`~parsec_tpu.tune.adaptive` — the generalized PR-12 EWMA
  controller resizing ``llm_steps_per_pool`` per tenant live
  (``tune_adaptive=1``), converged values written back to the DB.

``python -m parsec_tpu.tune --self-test`` runs the synthetic
quadratic-basin gate wired into ``scripts/check.sh``.
"""

from __future__ import annotations

from ..core.params import params as _params
from .db import TuneDB, best, cached_db, default_path, make_key  # noqa: F401
from .signature import (ambient_signature, size_bucket,  # noqa: F401
                        workload_signature)

__all__ = ["TuneDB", "best", "cached_db", "default_path", "make_key",
           "ambient_signature", "size_bucket", "workload_signature",
           "search", "KnobController", "apply_ambient", "consult_ambient"]


def __getattr__(name: str):
    # the heavy halves load on first use: importing parsec_tpu.tune from
    # Context.__init__ must not drag the search/adaptive machinery in
    if name == "search":
        from .search import search
        return search
    if name == "KnobController":
        from .adaptive import KnobController
        return KnobController
    raise AttributeError(name)


def consult_ambient(tag: str, *, objective: str | None = None
                    ) -> dict | None:
    """The stored knob vector for an ambient tag (``context``,
    ``tenant:<t>``), or ``None``: gate (``tune_db``), cached-store probe,
    declared-knob filter — but no application.  Any objective matches
    when ``objective`` is None (ambient tags rarely carry more than
    one)."""
    if not _params.get("tune_db"):
        return None
    try:
        db = cached_db()
        sig = ambient_signature(tag)
        if objective is not None:
            rec = db.best(sig, objective=objective)
        else:
            rec = None
            for r in db._index().values():
                if r.get("sig") == sig:
                    rec = r if rec is None or r["ts"] > rec["ts"] else rec
    except Exception:                   # noqa: BLE001 — a corrupt DB must
        return None                     # never fail a Context start
    if rec is None:
        return None
    space = _params.knob_space()
    knobs = {n: v for n, v in rec["knobs"].items()
             if n in space and space[n].contains(v)}
    return knobs or None


def apply_ambient(tag: str) -> dict | None:
    """Consult + APPLY: set every declared, registered knob from the
    stored vector — skipping knobs the operator pinned via env/cli (an
    explicit override always wins over a persisted tuning).  Returns
    the dict actually applied, or ``None`` on miss/disabled."""
    knobs = consult_ambient(tag)
    if not knobs:
        return None
    applied: dict = {}
    for name, value in knobs.items():
        p = _params.lookup(name)
        if p is None:                   # owning module not loaded yet:
            continue                    # nothing to apply the knob to
        if p.source in ("env", "cli"):
            continue
        try:
            _params.set(name, value)
            applied[name] = _params.get(name)
        except Exception:               # noqa: BLE001 — one bad knob must
            continue                    # not lose the rest of the vector
    return applied or None
