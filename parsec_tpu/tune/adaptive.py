"""Live knob adaptation: the PR-12 EWMA controller, generalized
(ISSUE 18).

The speculative-decode adapter (``llm/batcher.py:_spec_draft`` /
``_note_spec``) converged one knob per stream from an observed signal
with three ingredients: an EWMA fold of the signal, **hysteresis** (a
move needs a real margin, so noise never flaps the knob), and
**staggered probes** (a converged knob re-tests a neighbor on a bounded
cadence, offset per owner so probes don't align).  :class:`KnobController`
is that pattern extracted over an arbitrary integer knob and an
arbitrary scalar objective.

The shipped user is per-tenant ``llm_steps_per_pool``: the batcher's
iteration loop feeds each tenant's observed inter-token latency
(exactly what lands in its LogHistogram on the SLO plane) into one
controller per tenant and applies the controller's value when sizing
that tenant's next decode superpool.  The knob moves BATCHING, never
tokens — a stream's output is oracle-equal token-for-token whatever the
controller does, which is what makes live adaptation safe to leave on.
A converged controller writes its value back to the tuning DB
(``ambient:tenant:<t>``), where the next server's per-tenant consult
starts from it.

MCA knob: ``tune_adaptive`` (default OFF — the k sweep in microbench
and any explicit ``llm_steps_per_pool`` setting must stay authoritative
unless the operator opts in).
"""

from __future__ import annotations

import math

from ..core.params import params as _params
from .db import TuneDB
from .signature import ambient_signature

_params.register("tune_adaptive", False,
                 "live per-tenant adaptation of llm_steps_per_pool from "
                 "the observed inter-token latency (tune/adaptive."
                 "KnobController): converged values persist to the "
                 "tuning DB.  Off by default: explicit "
                 "llm_steps_per_pool settings and sweeps stay "
                 "authoritative unless the operator opts in")

# concurrency contract (analysis.runtimelint, docs/ANALYSIS.md): this
# module owns NO shared mutable state — every KnobController is
# single-owner by design (one tenant's batcher loop drives it; see the
# class docstring), and persistence goes through tune/db.py's guarded
# cache.  The empty registry is the declaration: nothing here may grow
# cross-thread mutation without also growing a lock and an entry.
_LOCK_PROTECTED = {}
_LOCK_ORDER = ()

# controller cadence: how many observations one probe holds, and how
# many observations a converged knob waits before probing again
PROBE_LEN = 8
PROBE_EVERY = 64
# hysteresis: a probe must beat the incumbent EWMA by this relative
# margin to be adopted — flapping costs more than a slightly-suboptimal
# plateau (the PR-12 0.6/0.35 band, expressed relatively)
HYSTERESIS = 0.10
# consecutive garbage (non-finite / non-positive) observations before
# the controller abandons adaptation and falls back to the default —
# the PR-12 garbage-drafter shape: a broken objective must cost a
# bounded number of probes, then leave the knob alone
GARBAGE_LIMIT = 8


class KnobController:
    """Hysteresis EWMA controller over one integer knob.

    ``observe(objective)`` folds one observation of the signal measured
    UNDER the current :attr:`value` and returns the value to apply next.
    Not thread-safe — each owner (one tenant's batcher loop) drives its
    own controller."""

    def __init__(self, name: str, default: int, lo: int, hi: int, *,
                 better: str = "lower", alpha: float = 0.3,
                 probe_every: int = PROBE_EVERY,
                 probe_len: int = PROBE_LEN, stagger: int = 0) -> None:
        self.name = name
        self.default = int(default)
        self.lo, self.hi = int(lo), int(hi)
        self.value = max(self.lo, min(self.hi, int(default)))
        self.better = better
        self.alpha = alpha
        self.probe_every = max(1, probe_every)
        self.probe_len = max(1, probe_len)
        self._ewma: dict[int, float] = {}
        self._incumbent = self.value
        self._probing: int | None = None
        self._probe_seen = 0
        # staggered: a fleet of controllers (one per tenant) offsets its
        # first probe so they never all probe on the same iteration
        self._since_probe = stagger % self.probe_every
        self._probe_dir = 1             # alternate up/down candidates
        self._garbage = 0
        self.dead = False               # garbage objective: adaptation off
        self.probes = 0
        self.adoptions = 0
        self._dirty = False             # converged movement not yet persisted

    # -- the fold --------------------------------------------------------
    def observe(self, objective: float) -> int:
        if self.dead:
            return self.value
        if not isinstance(objective, (int, float)) \
                or not math.isfinite(float(objective)) or objective <= 0.0:
            self._garbage += 1
            if self._garbage >= GARBAGE_LIMIT:
                # bounded fallback: stop moving, return to the default
                self.dead = True
                self.value = self.default
                self._probing = None
            return self.value
        self._garbage = 0
        x = float(objective)
        m = self._ewma.get(self.value)
        self._ewma[self.value] = x if m is None \
            else m + self.alpha * (x - m)
        if self._probing is not None:
            self._probe_seen += 1
            if self._probe_seen >= self.probe_len:
                self._settle_probe()
            return self.value
        self._since_probe += 1
        if self._since_probe >= self.probe_every:
            self._start_probe()
        return self.value

    # -- probes ----------------------------------------------------------
    def _candidate(self) -> int | None:
        for _ in range(2):              # try one direction, then the other
            c = (self._incumbent * 2 if self._probe_dir > 0
                 else self._incumbent // 2)
            self._probe_dir = -self._probe_dir
            c = max(self.lo, min(self.hi, c))
            if c != self._incumbent:
                return c
        return None

    def _start_probe(self) -> None:
        self._since_probe = 0
        cand = self._candidate()
        if cand is None:
            return
        self._probing = cand
        self._probe_seen = 0
        self.value = cand
        self.probes += 1

    def _settle_probe(self) -> None:
        cand = self._probing
        self._probing = None
        self._probe_seen = 0
        inc = self._ewma.get(self._incumbent)
        got = self._ewma.get(cand)
        adopt = False
        if inc is None:
            adopt = True
        elif got is not None:
            adopt = (got > inc * (1 + HYSTERESIS) if self.better == "higher"
                     else got < inc * (1 - HYSTERESIS))
        if adopt:
            self._incumbent = cand
            self.adoptions += 1
            self._dirty = True
        self.value = self._incumbent

    # -- state -----------------------------------------------------------
    @property
    def converged(self) -> bool:
        """Between probes at a settled incumbent (or dead): the value is
        stable enough to persist."""
        return self.dead or (self._probing is None
                             and self._incumbent in self._ewma)

    def take_writeback(self) -> int | None:
        """The converged value to persist, exactly once per adoption
        (``None`` = nothing new)."""
        if self._dirty and self.converged and self._probing is None:
            self._dirty = False
            return self._incumbent
        return None

    def ewma_of(self, value: int) -> float | None:
        return self._ewma.get(value)

    def stats(self) -> dict:
        return {"value": self.value, "incumbent": self._incumbent,
                "probes": self.probes, "adoptions": self.adoptions,
                "dead": self.dead}


def steps_controller(tenant: str, default: int, *, lo: int = 1,
                     hi: int = 32) -> KnobController:
    """The per-tenant ``llm_steps_per_pool`` controller the batcher
    creates lazily: objective = observed inter-token ms (lower better),
    stagger keyed off the tenant name so a fleet's probes interleave."""
    return KnobController("llm_steps_per_pool", default, lo, hi,
                          better="lower", stagger=abs(hash(tenant)))


def writeback(tenant: str, value: int, score: float, *,
              db: TuneDB | None = None) -> None:
    """Persist a converged per-tenant value under the tenant's ambient
    signature; best-effort (a read-only artifact dir must never fail
    the decode loop)."""
    try:
        (db or TuneDB()).note(ambient_signature(f"tenant:{tenant}"),
                              {"llm_steps_per_pool": int(value)},
                              float(score), objective="tok_latency_ms",
                              source="adaptive")
    except Exception:                   # noqa: BLE001 — advisory only
        pass
