"""Continuous batching: the LLM session layer over a RuntimeServer.

Orca-style iteration-level scheduling on the serving layer's own
primitives: clients open *streams* (:meth:`ContinuousBatcher
.submit_stream` — surfaced as ``RuntimeServer.submit_stream``), and one
batcher thread runs the decode loop::

    each iteration:
      admit newly-arrived streams   -> submit prefill pools (PF tasks)
      group live streams by tenant  -> ONE k-step decode SUPERPOOL per
                                       tenant (llm_steps_per_pool)
      await decode, read TOK tiles  -> k tokens per stream per submit
      await prefill (it OVERLAPPED the decode superpool), join streams
      retire finished streams       -> kv.free_seq (pages recycle)

The superpool (ISSUE 9) is the amortization move: sampling runs
IN-GRAPH (the SAMPLE task class, ``llm/decode.decode_superpool_ptg``),
so one pool spans ``llm_steps_per_pool`` autoregressive iterations and
the per-pool submit/termdet overhead (~1-2 ms) is paid once per k
tokens, not once per token.  With ``llm_spec_k`` set, streams whose
n-gram drafter has a proposal ride a **speculative superpool** instead
(ISSUE 12, ``llm/decode.spec_superpool_ptg``): the draft's 1+k
positions verify in one batched ragged-attention pass with NO serial
sample chain, the in-graph VERIFY class computes the accepted prefix,
and the rejected tail's speculative KV appends roll back
(``PagedKVCollection.rollback_tail``) before the next pool — per-stream
draft length adapts live from the observed acceptance rate.  EOS and early-finishing streams ride
predicated step bodies — a finished stream's remaining tasks no-op, so
it wastes at most its own tail tasks.  Prefill pools for arriving
streams are submitted BEFORE the decode superpools are awaited, so a
long prompt's chunked prefill overlaps a whole k-step iteration instead
of stalling it; new streams join at the next iteration boundary and
finished streams leave without stalling the batch — with admission
control bounding in-flight pools and WFQ arbitrating decode against
whatever dense-linear-algebra tenants share the server (the soak test
mixes decode with a Cholesky pool, ``tests/test_llm_serve.py``).

Every superpool is a fresh PTG taskpool: the live re-enqueue path PR 3
built (``Context.add_taskpool`` under ``_submit_lock``) runs once per
k-token batch, and terminated pools retire from the process registry
(``runtime/taskpool.py``) so a million-token serving run's footprint
stays bounded by LIVE streams, not by history.  ``fork_from=`` forks a
stream's prompt KV copy-on-write from an already-admitted stream with
the same prompt (``PagedKVCollection.fork``): N continuations share ONE
physical copy of the prompt pages until their first divergent write.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import deque
from typing import Any, Sequence

import numpy as np

from ..core.future import Future
from ..core.params import params as _params
from ..data.datatype import TileType
from ..prof import spans as _spans
from ..data_dist.collection import DictCollection
from ..data_dist.kv_tiers import KVTierMap
from ..data_dist.paged_kv import PagedKVCollection
from .decode import (decode_superpool_ptg, preallocate_decode_steps,
                     prefill_chunks, prefill_ptg, read_spec_batched,
                     read_token_chain, seed_emb_table, seed_spec_batched,
                     seed_stream_step, spec_batched_ptg)
from .model import NgramDrafter, ToyLM
from .prefix_tree import PrefixTree

_params.register("llm_page_size", 16,
                 "tokens per KV page (PagedKVCollection block size)")
_params.register("llm_max_batch", 32,
                 "live decode streams a batcher serves concurrently; "
                 "arrivals beyond it queue for the next free slot")
_params.register("llm_max_pages", 4096,
                 "physical KV pages the batcher's cache may hold")
_params.register("llm_step_timeout", 60.0,
                 "seconds the batcher waits for one decode-step pool "
                 "before failing the streams riding it")
_params.register("llm_steps_per_pool", 8,
                 "autoregressive decode steps one superpool spans (the "
                 "in-graph SAMPLE class carries token -> next query "
                 "between steps): the host loop and its submit/termdet "
                 "overhead run once per k tokens; 1 = the PR-6 "
                 "step-per-pool behavior")
# the autotuner's declared domain (docs/TUNING.md): superpool depth
# moves in powers of two; past ~32 the step-timeout and per-stream
# budget clipping dominate, so the search never wanders further
_params.declare_knob("llm_steps_per_pool", lo=1, hi=32, scale="log2")
_params.register("llm_compiled_pools", True,
                 "submit decode superpools through the funneled "
                 "compiled-DAG executor (runtime/dagrun.py, PR 2's "
                 "native select->release loop) instead of the dynamic "
                 "scheduler: lowest per-task overhead, at the cost of "
                 "task-grain WFQ interleaving WITHIN a pool (tenant "
                 "fairness still applies across pools)")
_params.register("llm_lower_regions", False,
                 "region-lower each decode superpool (ptg.lowering."
                 "lower_regions) before submission: per-step XLA "
                 "dispatches collapse into one jitted program per "
                 "verified region (compile cost rides the lowering "
                 "cache / AOT warming; pools that cannot lower fall "
                 "back to the dynamic path)")
_params.register("llm_spec_k", 0,
                 "speculative decode (ISSUE 12): draft tokens the "
                 "per-stream n-gram drafter proposes per superpool "
                 "(0 = off).  A spec superpool verifies 1+k positions "
                 "in ONE batched ragged-attention pass — every "
                 "position's query is known at build time, so the "
                 "PR-9 serial SAMPLE chain disappears; the in-graph "
                 "VERIFY chain predicates rejected tails off and the "
                 "batcher rolls their speculative KV appends back "
                 "(PagedKVCollection.rollback_tail)")
_params.register("llm_spec_adaptive", True,
                 "adapt each stream's draft length within "
                 "[0, llm_spec_k] from its observed acceptance-rate "
                 "EWMA: draftable traffic grows toward the cap, "
                 "undraftable traffic converges to 0 and falls back "
                 "to the non-speculative k-step superpool (with a "
                 "periodic cheap probe), so acceptance-rate-0 traffic "
                 "degrades to the PR-9 path instead of paying "
                 "rejected drafts forever")
_params.register("llm_prefetch_ahead", True,
                 "stage live streams' device-evicted KV pages back in "
                 "one superpool ahead of the decode wavefront (the "
                 "kv_tiers.KVTierMap return path): the async device_put "
                 "overlaps the in-flight superpools, so an HBM budget "
                 "below the working set costs bandwidth, not stalls")

# live batchers, weakly held: runtime_report()["llm"] aggregates their
# cache/tier effectiveness without pinning a stopped batcher (or
# importing this module when no LLM workload ever ran).  A stopping
# batcher folds its final counters into _retired_totals so the report
# stays cumulative-since-process-start like every other block (a bench
# stage's drained servers still show up in the post-stage report).
_live_batchers: "weakref.WeakSet[ContinuousBatcher]" = weakref.WeakSet()
_retired_totals: dict[str, int] = {}
_retired_lock = threading.Lock()

_REPORT_KEYS = ("tokens_generated", "streams_completed", "decode_submits",
                "forked_streams", "prefill_tokens_total",
                "prefill_tokens_skipped", "spec_submits", "spec_tokens",
                "spec_drafted", "spec_drafts_accepted")
_REPORT_KV_KEYS = ("prefix_hits", "prefix_pages_reused", "host_tier_bytes",
                   "prefetch_inflight", "physical_pages", "cow_copies",
                   "tail_rollbacks", "slots_rolled_back")

# iterations a converged-off adaptive stream waits before probing spec
# again (2 small probe pools per interval; at k=8 plain pools the probe
# tax is ~3% of throughput — inside the acceptance-rate-0 10% budget)
_SPEC_PROBE_EVERY = 64


def _fold_stats(out: dict, s: dict) -> None:
    for k in _REPORT_KEYS:
        out[k] = out.get(k, 0) + s.get(k, 0)
    for k in _REPORT_KV_KEYS:
        out[k] = out.get(k, 0) + s.get("kv", {}).get(k, 0)


def aggregate_report() -> dict:
    """The ``llm`` block of ``prof.runtime_report()``: counters summed
    across every live batcher plus the folded totals of retired ones —
    present in a report only when this module is already imported AND
    an LLM workload actually ran."""
    with _retired_lock:
        out: dict[str, Any] = dict(_retired_totals)
    for b in list(_live_batchers):
        if not getattr(b, "_folded", False):
            _fold_stats(out, b.stats())
    if out:
        total = out.get("prefill_tokens_total", 0)
        out["prefill_skipped_frac"] = round(
            out.get("prefill_tokens_skipped", 0) / total, 4) if total \
            else 0.0
        # the speculative-decode effectiveness pair (ISSUE 12): how
        # often drafts were right, and how many tokens one spec
        # superpool ride yields per stream — cumulative like the rest
        if out.get("spec_drafted"):
            out["spec_accept_rate"] = round(
                out.get("spec_drafts_accepted", 0)
                / out["spec_drafted"], 4)
        if out.get("spec_submits"):
            out["spec_tokens_per_submit"] = round(
                out.get("spec_tokens", 0) / out["spec_submits"], 4)
    return out


class StreamTicket:
    """One generation stream's handle.  ``tokens`` grows live — snapshot
    with :meth:`generated`; ``result()`` blocks for the finished
    transcript."""

    def __init__(self, name: str, tenant: str) -> None:
        self.name = name
        self.tenant = tenant
        self.state = "queued"
        self.submitted_at = time.monotonic()
        self.tokens: list[int] = []
        self.per_token_s: list[float] = []
        self.prefill_s: float | None = None
        self.first_token_at: float | None = None   # monotonic TTFT stamp
        self.prefix_pages_reused = 0   # trie pages this stream skipped
        # speculative-decode visibility (ISSUE 12): the stream's current
        # (possibly adapted) draft cap and its acceptance-rate EWMA,
        # updated after every spec superpool it rides
        self.spec_k: int | None = None
        self.spec_accept_ewma: float | None = None
        # the stream's trace context (prof/spans.py): the request-scoped
        # identity of this generation, named by stall dumps and carried
        # by every decode superpool ticket the stream rides
        self.trace = _spans.new_trace()
        self._future: Future = Future()

    def generated(self) -> list[int]:
        """Snapshot of the tokens generated so far (the batcher appends
        concurrently; ``list()`` of a list is atomic under the GIL)."""
        return list(self.tokens)

    def result(self, timeout: float | None = None) -> dict:
        """Block for completion; returns ``{"tokens": [...],
        "per_token_s": [...], "prefill_s": ...}``."""
        kind, v = self._future.get(timeout)
        if kind == "err":
            raise v
        return v

    def done(self) -> bool:
        return self._future.is_ready()

    def _resolve(self) -> None:
        self.state = "done"
        self._future.set(("ok", {"tokens": list(self.tokens),
                                 "per_token_s": list(self.per_token_s),
                                 "prefill_s": self.prefill_s}))

    def _fail(self, e: BaseException) -> None:
        self.state = "failed"
        self._future.set(("err", e))


class _Stream:
    __slots__ = ("seq", "tenant", "priority", "prompt", "max_new",
                 "ticket", "cur", "devices", "eos", "fork_from", "k",
                 "spec", "drafter", "spec_k", "spec_ewma", "spec_probe")

    def __init__(self, seq: Any, tenant: str, priority: int,
                 prompt: Sequence[int], max_new: int,
                 ticket: StreamTicket, eos: int | None = None,
                 fork_from: "_Stream | None" = None) -> None:
        self.seq = seq
        self.tenant = tenant
        self.priority = priority
        self.prompt = list(prompt)
        self.max_new = max_new
        self.ticket = ticket
        self.cur = int(prompt[-1])
        self.eos = None if eos is None else int(eos)
        self.fork_from = fork_from      # CoW prompt-KV parent (or None)
        self.k = 1                      # steps the current superpool runs
        self.spec = False               # current pool is speculative
        # the stream's drafter, built LAZILY in the batcher thread the
        # first time speculation considers this stream (llm_spec_k off
        # = never): submit_stream stays O(1) — client-side prompt
        # walking here widens the fork-classification arrival window
        self.drafter: NgramDrafter | None = None
        self.spec_k = -1                # adaptive draft cap (-1 = unset)
        self.spec_ewma = -1.0           # acceptance EWMA (-1 = unset)
        self.spec_probe = 0             # iterations since converged off


class ContinuousBatcher:
    """The decode loop.  Owns the paged KV cache plus the Q/O side
    collections; rides an existing :class:`RuntimeServer` for admission,
    fairness, and the hot context."""

    def __init__(self, server: Any, model: ToyLM | None = None,
                 kv: PagedKVCollection | None = None,
                 max_batch: int | None = None,
                 devices: str = "cpu",
                 owner_rank: int | None = None) -> None:
        self._server = server
        self.model = model or ToyLM()
        H, D = self.model.num_heads, self.model.head_dim
        # owner_rank pins EVERY collection tile to one rank of a
        # multirank context: decode pools are submitted on this rank
        # only (sharded serving, serve/sharded.py), so a default-owned
        # (rank 0) tile on any other rank would shell the whole batch
        # out to a rank that never enqueued the pool
        self.owner_rank = owner_rank
        _pin = None if owner_rank is None else (lambda *k: owner_rank)

        def _dc(name: str, dtt: TileType) -> DictCollection:
            return DictCollection(name, dtt=dtt, rank_of_fn=_pin)

        self.kv = kv or PagedKVCollection(
            "llmKV", page_size=_params.get("llm_page_size"),
            num_heads=H, head_dim=D,
            max_pages=_params.get("llm_max_pages"),
            rank_of_fn=None if owner_rank is None
            else (lambda seq, page: owner_rank))
        assert (self.kv.num_heads, self.kv.head_dim) == (H, D), \
            "model and KV cache disagree on head geometry"
        self.Q = _dc("llmQ", TileType((3, H, D), np.float32))
        self.O = _dc("llmO", TileType((H, D), np.float32))
        # the in-graph SAMPLE class's side collections (ISSUE 9): TOK
        # carries the per-step [token, done, eos] chain tiles the host
        # reads once per superpool; EMB holds the precomputed q3 stack
        # table the SAMPLE kernel computes logits/next-queries from
        # (one gather per token — ToyLM.q3_table)
        self.TOK = _dc("llmTOK", TileType((3,), np.float32))
        # the batched speculative superpool's side collections (ISSUE
        # 12, llm/decode.spec_batched_ptg): QS the per-position query
        # stacks (position 0 the real current token, 1.. the drafter's
        # proposals), LIM the per-(seq, page) causal slot limits, DTOKS
        # the packed draft chain the SVERIFY body compares, VOUT the
        # accepted-prefix result the host reads once per spec pool.
        # Tile shapes are per-pool (padded to llm_spec_k + 1); the
        # declared dtts only serve lazy zero-init before the first seed
        sp0 = max(1, int(_params.get("llm_spec_k"))) + 1
        self.QS = _dc("llmQS", TileType((sp0, 3, H, D), np.float32))
        self.LIM = _dc("llmLIM", TileType((sp0,), np.float32))
        self.DTOKS = _dc("llmDTOKS", TileType((sp0 + 2,), np.float32))
        self.VOUT = _dc("llmVOUT", TileType((sp0 + 2,), np.float32))
        self.EMB = _dc(
            "llmEMB", TileType(self.model.q3_table().shape, np.float32))
        seed_emb_table(self.model, self.EMB)
        self.max_batch = max_batch or _params.get("llm_max_batch")
        self.devices = devices
        # the ISSUE-11 memory hierarchy: an automatic prefix cache over
        # the KV collection (llm_prefix_cache — retired streams donate
        # their prompt pages, arrivals fork the longest retained
        # prefix), and a tier map accounting device-evicted pages +
        # staging them back ahead of the wavefront
        self.prefix = (PrefixTree(self.kv)
                       if _params.get("llm_prefix_cache") else None)
        self.tiers = KVTierMap(self.kv)
        self.prefill_tokens_total = 0     # cacheable tokens admitted
        self.prefill_tokens_skipped = 0   # of those, served by the trie
        # the server's per-tenant SLO plane (prof/histogram.py): TTFT +
        # inter-token latency land there, so RuntimeServer.metrics()
        # answers "what are my per-tenant token p99s" live mid-run
        self._slo = getattr(server, "_slo", None)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._pending: deque[_Stream] = deque()
        self._live: list[_Stream] = []
        self._seq_ids = itertools.count()
        self._stop = False
        self._abort: BaseException | None = None
        self.steps = 0
        self.tokens_generated = 0
        self.streams_completed = 0
        self.decode_submits = 0         # superpool submits (1/k per token)
        self.forked_streams = 0         # streams whose prompt KV forked
        # speculative-decode tallies (ISSUE 12): spec_submits counts
        # per-stream spec-superpool rides (the unit spec_tokens_per_
        # submit amortizes over), spec_drafted/accepted the drafter's
        # proposal hit rate
        self.spec_submits = 0
        self.spec_tokens = 0
        self.spec_drafted = 0
        self.spec_drafts_accepted = 0
        # per-tenant acceptance prior (batcher thread only): new streams
        # start their adaptive draft cap where the tenant's traffic
        # converged, so undraftable workloads don't pay the cap->0
        # descent once per stream — only the staggered probes remain
        self._spec_prior: dict[str, float] = {}
        # per-tenant live adaptation of llm_steps_per_pool (ISSUE 18,
        # ``tune_adaptive=1``): one hysteresis EWMA controller per
        # tenant (tune/adaptive.KnobController), fed the same observed
        # inter-token latency the SLO plane quantiles.  _k_seed holds
        # the tuning-DB start points RuntimeServer's per-tenant consult
        # hands over before the controller exists (GIL-atomic dict
        # writes; controllers themselves live on the batcher thread)
        self._k_ctl: dict[str, Any] = {}
        self._k_seed: dict[str, int] = {}
        self._pool_seq = itertools.count()
        _live_batchers.add(self)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-batcher")
        self._thread.start()

    # -- client API ------------------------------------------------------
    def submit_stream(self, prompt_tokens: Sequence[int],
                      max_new_tokens: int = 16, tenant: str = "default",
                      priority: int = 0, eos: int | None = None,
                      fork_from: StreamTicket | None = None
                      ) -> StreamTicket:
        """Open one generation stream; it joins the running batch at the
        next iteration boundary.

        ``eos`` stops generation early when sampled (the EOS token is
        the last one kept; handled in-graph by the predicated SAMPLE
        bodies, so a mid-superpool finish wastes no other stream's
        work).  ``fork_from`` names an earlier stream's ticket with the
        SAME prompt: the new stream forks its prompt KV copy-on-write
        (``PagedKVCollection.fork``) instead of re-prefilling — N
        continuations of one prompt hold one physical copy of the
        prompt pages until their first divergent write.  When the
        parent has already advanced past its prompt (or retired), the
        fork silently falls back to a normal prefill."""
        if not prompt_tokens:
            raise ValueError("prompt_tokens must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        parent = None
        if fork_from is not None:
            parent = getattr(fork_from, "_stream", None)
            # identity, not shape: another batcher's seq ids collide
            # with ours, so a foreign ticket could fork an UNRELATED
            # local sequence's pages
            if parent is None or getattr(fork_from, "_batcher",
                                         None) is not self:
                raise ValueError("fork_from must be a StreamTicket from "
                                 "this batcher")
            if parent.prompt != list(prompt_tokens):
                raise ValueError("fork_from requires an identical prompt "
                                 "(the shared-prefix pages ARE the fork)")
        seq = next(self._seq_ids)
        ticket = StreamTicket(f"stream{seq}", tenant)
        st = _Stream(seq, tenant, priority, prompt_tokens,
                     max_new_tokens, ticket, eos=eos, fork_from=parent)
        ticket._stream = st
        ticket._batcher = self
        with self._lock:
            if self._stop:
                # typed shed, same contract as server.submit: clients
                # catching AdmissionRejected to back off keep working
                # through the drain window
                from ..serve.admission import AdmissionRejected
                raise AdmissionRejected("llm batcher is stopped")
            self._pending.append(st)
        self._wake.set()
        return ticket

    def seed_tenant_knobs(self, tenant: str, knobs: dict) -> None:
        """Seed a tenant's adaptive start point from a persisted knob
        vector (the RuntimeServer per-tenant tuning-DB consult) —
        consumed when that tenant's controller is created lazily."""
        k = knobs.get("llm_steps_per_pool")
        if isinstance(k, (int, float)) and not isinstance(k, bool) \
                and k >= 1:
            self._k_seed[tenant] = int(k)

    def _tenant_k(self, tenant: str, k_max: int) -> int:
        """The tenant's pool depth this iteration: the global
        ``llm_steps_per_pool`` unless live adaptation is on, then the
        tenant's controller value (seeded from the tuning DB when a
        vector was stored).  Batcher thread only."""
        if not _params.get("tune_adaptive", False):
            return k_max
        ctl = self._k_ctl.get(tenant)
        if ctl is None:
            from ..tune.adaptive import steps_controller
            ctl = steps_controller(tenant, self._k_seed.get(tenant, k_max))
            self._k_ctl[tenant] = ctl
        return max(1, int(ctl.value))

    # -- placement hooks (serve/sharded.py) ------------------------------
    def residency_len(self, prompt_tokens) -> int:
        """How many leading TOKENS of a prospective prompt are already
        resident in this batcher's prefix trie (full pages only) — the
        KV-residency signal the sharded placement router maximizes.  0
        with the prefix cache off."""
        if self.prefix is None:
            return 0
        _seq, pages = self.prefix.match(list(prompt_tokens))
        return pages * self.kv.page_size

    def load(self) -> dict:
        """Live + queued stream counts — the sharded router's
        least-loaded fallback signal."""
        with self._lock:
            return {"live": len(self._live), "queued": len(self._pending)}

    def stats(self) -> dict:
        with self._lock:
            out = {
                "live_streams": len(self._live),
                "queued_streams": len(self._pending),
                "steps": self.steps,
                "tokens_generated": self.tokens_generated,
                "streams_completed": self.streams_completed,
                "decode_submits": self.decode_submits,
                "forked_streams": self.forked_streams,
                "prefill_tokens_total": self.prefill_tokens_total,
                "prefill_tokens_skipped": self.prefill_tokens_skipped,
                "spec_submits": self.spec_submits,
                "spec_tokens": self.spec_tokens,
                "spec_drafted": self.spec_drafted,
                "spec_drafts_accepted": self.spec_drafts_accepted,
            }
        if out["spec_drafted"]:
            out["spec_accept_rate"] = round(
                out["spec_drafts_accepted"] / out["spec_drafted"], 4)
        if out["spec_submits"]:
            out["spec_tokens_per_submit"] = round(
                out["spec_tokens"] / out["spec_submits"], 4)
        if self._k_ctl:
            out["adaptive_k"] = {t: c.stats()
                                 for t, c in self._k_ctl.items()}
        out["kv"] = self.kv.stats()
        out["tiers"] = self.tiers.stats()
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        return out

    def stop(self, timeout: float | None = 60.0) -> None:
        """Graceful: no new streams, finish the live ones, join.  On
        timeout the loop is aborted and leftover streams fail."""
        with self._lock:
            self._stop = True
        self._wake.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            self._abort = RuntimeError("batcher stop timed out")
            self._wake.set()
            self._thread.join(5.0)
        # fold the final counters into the process aggregate exactly
        # once, so runtime_report()["llm"] stays cumulative after this
        # batcher (and its server) are gone
        with _retired_lock:
            if not getattr(self, "_folded", False):
                self._folded = True
                _fold_stats(_retired_totals, self.stats())

    # -- the iteration loop ---------------------------------------------
    def _loop(self) -> None:
        try:
            while True:
                if self._abort is not None:
                    # checked BEFORE popping arrivals: _fail_all covers
                    # _live + _pending, so anything popped here would
                    # slip through with an unresolved ticket
                    self._fail_all(self._abort)
                    return
                with self._lock:
                    room = self.max_batch - len(self._live)
                    fresh = [self._pending.popleft()
                             for _ in range(min(room, len(self._pending)))]
                    live = list(self._live)
                    stopping = self._stop
                if not fresh and not live:
                    if stopping:
                        return
                    self._wake.wait(0.05)
                    self._wake.clear()
                    continue
                # chunked-prefill interleave (ISSUE 9): arrivals' prefill
                # pools are SUBMITTED first, the live streams' k-step
                # decode superpools run while prefill is in flight, and
                # only then are the prefill tickets awaited — a long
                # prompt overlaps a whole decode iteration instead of
                # stalling it.  Fresh streams join at the NEXT boundary.
                pf = self._prefill_submit(fresh) if fresh else None
                if live:
                    self._decode_step(live)
                if pf is not None:
                    ok = self._prefill_await(pf)
                    with self._lock:
                        self._live.extend(ok)
        except BaseException as e:      # noqa: BLE001 — fail the streams,
            self._fail_all(e)           # never leave clients blocked

    def _retire_failed(self, streams: list[_Stream], e: BaseException,
                       defer_pool: Any = None) -> None:
        """Contain a failure to the streams it actually hit: one tenant's
        shed pool (admission timeout), one stream's exhausted page budget
        — the OTHER tenants' streams keep decoding.

        ``defer_pool`` must be passed when the streams' pool may STILL BE
        RUNNING (a step-timeout: serve tickets cannot cancel a live DAG):
        freeing the KV pages immediately would hand them to a new stream
        while the zombie pool's OUT tasks can still write into them —
        the pages release only when that pool actually terminates (the
        listener fires immediately if it already has)."""
        with self._lock:
            for st in streams:
                if st in self._live:
                    self._live.remove(st)
        seqs = [st.seq for st in streams]
        for st in streams:
            st.ticket._fail(e)
        if defer_pool is None:
            for s in seqs:
                self._release_stream_state(s)
        else:
            defer_pool.add_completion_listener(
                lambda _tp: [self._release_stream_state(s) for s in seqs])

    def _release_stream_state(self, seq: Any) -> None:
        """Everything a retired sequence held: KV pages back to the free
        list, its Q/O side tiles and TOK chain tiles dropped — the
        serving footprint must be bounded by LIVE streams, not by every
        stream ever served.  Safe for a never-allocated seq (all
        no-ops)."""
        self.kv.free_seq(seq)
        self.Q.discard(seq)
        self.O.discard(seq)
        for coll in (self.TOK, self.QS, self.LIM, self.DTOKS,
                     self.VOUT):
            for key in coll.known_keys():
                if key and key[0] == seq:
                    coll.discard(*key)

    def _fail_all(self, e: BaseException) -> None:
        with self._lock:
            victims = self._live + list(self._pending)
            self._live = []
            self._pending.clear()
        for st in victims:
            st.ticket._fail(e)
            self._release_stream_state(st.seq)

    def _fork_ready(self, parent: _Stream) -> bool:
        """Whether a fork parent's cache is EXACTLY its prompt prefix
        (prefilled, not yet decoded) — the only window where forking the
        block table IS forking the prompt.  A retired parent is never
        ready even when its seq still exists: a FAILED parent's page
        release may be deferred behind a timed-out zombie pool that is
        still writing them, and the host-side ledger (advanced at chunk
        time, before any pool ran) cannot tell the difference."""
        if parent.ticket.done():
            return False
        try:
            return self.kv.seq_len(parent.seq) == len(parent.prompt) - 1
        except KeyError:                 # parent retired / never admitted
            return False

    def _admit_via_prefix(self, st: _Stream) -> int:
        """Materialize a fresh stream's sequence, through the prefix
        cache when enabled: the trie matches ``prompt[:-1]`` (the
        cacheable run) and forks the longest retained full-page prefix
        copy-on-write (``PagedKVCollection.fork_prefix``), so only the
        unmatched tail prefills.  Returns the number of pages reused
        (0 = miss or cache disabled — plain ``alloc_seq``)."""
        if self.prefix is None:
            self.kv.alloc_seq(st.seq)
            reused = 0
        else:
            reused = self.prefix.adopt(st.seq, st.prompt[:-1])
        cacheable = len(st.prompt) - 1
        skipped = reused * self.kv.page_size
        with self._lock:
            self.prefill_tokens_total += cacheable
            self.prefill_tokens_skipped += skipped
        if reused:
            st.ticket.prefix_pages_reused = reused
            if self._slo is not None:
                # the per-tenant cache-effectiveness counters (PR-10
                # SLO plane): operators read hit rates next to the TTFT
                # quantiles the hits are supposed to move
                self._slo.inc(st.tenant, "prefix_hits")
                self._slo.inc(st.tenant, "prefix_pages_reused", reused)
        return reused

    def _prefill_submit(self, fresh: list[_Stream]) -> dict:
        """Phase 1 of the chunked-prefill interleave: allocate pages and
        SUBMIT one PF pool per tenant, without awaiting — the caller
        runs the decode superpools while these are in flight.  An
        exhausted page budget fails ONE stream, a shed pool fails ONE
        tenant's arrivals, never the whole batch.  Fork-on-prompt
        children skip prefill entirely: a child of an already-admitted
        parent sitting at its prompt boundary forks HERE (before this
        iteration's decode can advance the parent); a child arriving in
        the same batch as its parent resolves in :meth:`_prefill_await`
        once the parent's pages are real."""
        stream_chunks: dict[Any, dict[tuple, np.ndarray]] = {}
        chunk_starts: dict[Any, int] = {}
        by_tenant: dict[str, list[_Stream]] = {}
        forks: list[_Stream] = []
        ok: list[_Stream] = []
        fresh_ids = {id(st) for st in fresh}
        for st in fresh:
            parent = st.fork_from
            if parent is not None and id(parent) in fresh_ids:
                # parent arrives in THIS batch: its pages are not real
                # until its PF pool completes — defer to _prefill_await
                st.ticket.state = "prefill"
                forks.append(st)
                continue
            if parent is not None and self._fork_ready(parent):
                # already-admitted parent sitting exactly at its prompt
                # boundary: fork NOW, before this iteration's decode
                # superpool advances it (the window that used to force
                # the fallback).  CoW keeps the snapshot honest — the
                # parent's next append privatizes ITS tail, the child
                # keeps the prompt pages.
                try:
                    self.kv.fork(parent.seq, st.seq)
                except BaseException as e:   # noqa: BLE001 — contain
                    self._retire_failed([st], e)
                    continue
                st.fork_from = None
                st.ticket.state = "prefill"
                with self._lock:
                    self.forked_streams += 1
                ok.append(st)
                continue
            st.fork_from = None          # parent advanced: plain prefill
            try:
                reused = self._admit_via_prefix(st)
                # tail-only prefill: chunk indices continue past the
                # trie-shared pages (prefill_chunks reads the page
                # count); a full-prefix hit leaves nothing to chunk
                stream_chunks[st.seq] = prefill_chunks(
                    self.model, self.kv, st.seq,
                    st.prompt[reused * self.kv.page_size:-1])
                chunk_starts[st.seq] = reused
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed([st], e)
                continue
            st.ticket.state = "prefill"
            by_tenant.setdefault(st.tenant, []).append(st)
        t0 = time.perf_counter()
        tickets: list[tuple[Any, Any, list[_Stream]]] = []
        done_t: dict[int, float] = {}
        for tenant, group in by_tenant.items():
            # only streams with tail chunks ride a PF pool: single-token
            # prompts cache nothing, and a FULL-prefix trie hit already
            # holds every cacheable page copy-on-write — both join the
            # batch with prefill_s = 0.0 instead of awaiting a pool
            ok.extend(st for st in group
                      if not stream_chunks.get(st.seq))
            group = [st for st in group if stream_chunks.get(st.seq)]
            seqs = [st.seq for st in group]
            if not seqs:
                continue
            # THIS group's chunks only: the T key space is what lowering
            # and operators may walk, so it must not declare other
            # tenants' (or failed streams') tiles
            chunks: dict[tuple, np.ndarray] = {}
            for st in group:
                chunks.update(stream_chunks.get(st.seq, {}))
            try:
                T = DictCollection(
                    f"llmT{next(self._pool_seq)}",
                    dtt=self.kv.default_dtt,
                    init_fn=lambda *k, _c=chunks: _c[k],
                    keys=list(chunks))
                tp = prefill_ptg(self.kv, T, seqs, devices=self.devices,
                                 name=f"llm_prefill{next(self._pool_seq)}",
                                 starts=[chunk_starts.get(s, 0)
                                         for s in seqs])
                # timestamp the pool's ACTUAL completion: the interleave
                # awaits only after the decode superpools, so awaiting
                # time would inflate prefill_s by a whole iteration
                tp.add_completion_listener(
                    lambda _tp, _d=done_t, _k=id(tp):
                    _d.setdefault(_k, time.perf_counter()))
                tickets.append((self._server.submit(
                    tp, tenant=tenant,
                    priority=max(st.priority for st in group)), tp, group))
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed(group, e)
        return {"t0": t0, "tickets": tickets, "ok": ok, "forks": forks,
                "fresh_ids": fresh_ids, "done_t": done_t}

    def _prefill_await(self, state: dict) -> list[_Stream]:
        """Phase 2: await the PF tickets, then resolve fork children —
        their parent's pages are real now, so ``PagedKVCollection.fork``
        shares them copy-on-write (no bytes move).  Returns the streams
        that join the live batch."""
        ok: list[_Stream] = list(state["ok"])
        for st in ok:
            # single-token prompts cache nothing; early (phase-1) forks
            # shared CoW — either way no bytes moved
            st.ticket.prefill_s = 0.0
        for tk, tp, group in state["tickets"]:
            try:
                tk.result(timeout=_params.get("llm_step_timeout"))
            except BaseException as e:       # noqa: BLE001 — contain
                # the pool may still be running past its timeout: page
                # release rides its completion, not this failure
                self._retire_failed(group, e, defer_pool=tp)
                continue
            # prefill cost = submit -> the pool's own completion stamp,
            # NOT this (post-decode) await instant
            dt = state["done_t"].get(
                id(tp), time.perf_counter()) - state["t0"]
            for st in group:
                st.ticket.prefill_s = dt
            ok.extend(group)
        ok_ids = {id(st) for st in ok}
        fallback: list[_Stream] = []
        for st in state["forks"]:
            parent = st.fork_from
            # deferred forks all have IN-BATCH parents (out-of-batch
            # parents forked at phase-1 classification), and an
            # in-batch parent must have actually COMPLETED its PF pool:
            # the host-side length ledger advances at chunk time,
            # BEFORE the pool runs, so _fork_ready alone cannot prove
            # the parent's pages hold real bytes (a timed-out PF pool
            # may still be writing them).  A miss takes the documented
            # silent fallback: the child re-prefills its own prompt
            # like any fresh stream.
            if not (id(parent) in ok_ids):
                st.fork_from = None
                fallback.append(st)
                continue
            try:
                self.kv.fork(parent.seq, st.seq)
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed([st], e)
                continue
            # never consulted post-fork: clearing it unpins the parent
            # _Stream chain (prompt, ticket, token lists) so footprint
            # stays bounded by LIVE streams even for fork-of-fork trees
            # whose leaf tickets clients keep alive
            st.fork_from = None
            st.ticket.prefill_s = 0.0     # CoW share: no bytes moved
            with self._lock:
                self.forked_streams += 1
            ok_ids.add(id(st))       # a fork of a fork resolves in order
            ok.append(st)
        if fallback:
            # fork_from is cleared, so the batch produces no new forks
            # and this recursion terminates after one level (and sets
            # the fallback streams' own prefill_s)
            ok.extend(self._prefill_await(self._prefill_submit(fallback)))
        for st in ok:
            st.ticket.state = "decoding"
        return ok

    def _maybe_lower_regions(self, tp: Any) -> Any:
        """Opt-in (``llm_lower_regions``): compile the superpool into
        megakernel regions (PR 8, ``ptg.lowering.lower_regions``) and
        submit the REGION pool instead — per-step XLA dispatches
        collapse into one jitted program per verified region, on top of
        the 1/k submit amortization.  The lowering cache and AOT warming
        (``scripts/warm_cache.sh llm_decode_k``) make repeat geometries
        compile-free; anything the lowering refuses runs the dynamic
        path unchanged."""
        if not _params.get("llm_lower_regions"):
            return tp
        from ..ptg.lowering import LoweringError, lower_regions
        try:
            plan = lower_regions(tp)
            plan.compile()
            table = plan.materialize_table()
            return plan.taskpool(table)
        except LoweringError:
            return tp

    def _spec_draft(self, st: _Stream, spec_cap: int,
                    adaptive: bool) -> list[int] | None:
        """Decide whether THIS stream's next superpool is speculative,
        and with what draft.  None = ride the non-speculative PR-9
        superpool (spec off, no remaining budget to draft into, the
        drafter has no proposal, or the adaptive controller converged
        the stream off).  A converged-off stream re-probes every
        ``_SPEC_PROBE_EVERY`` iterations with a 2-token draft and a
        neutral EWMA, so traffic that TURNS draftable is re-detected at
        a bounded (~3%) probe tax."""
        remaining = st.max_new - len(st.ticket.tokens)
        if spec_cap <= 0 or remaining <= 1:
            return None
        if st.drafter is None:
            # first speculative look at this stream: the drafter sees
            # every token the stream KEEPS, prompt first, then whatever
            # was already generated under non-speculative iterations —
            # the table tracks the true history whatever mode ran
            st.drafter = NgramDrafter()
            for t in st.prompt:
                st.drafter.observe(int(t))
            for t in st.ticket.tokens:
                st.drafter.observe(int(t))
        cap = min(spec_cap, remaining - 1)
        if adaptive:
            if st.spec_k < 0:
                # optimistic start at the cap — unless the tenant's
                # traffic already proved undraftable, then start OFF
                # (staggered so a tenant's probes don't align)
                prior = self._spec_prior.get(st.tenant)
                if prior is not None and prior < 0.35:
                    st.spec_k = 0
                    st.spec_probe = (hash(st.seq)
                                     % _SPEC_PROBE_EVERY)
                else:
                    st.spec_k = spec_cap
            if st.spec_k == 0:
                st.spec_probe += 1
                if st.spec_probe < _SPEC_PROBE_EVERY:
                    return None
                st.spec_probe = 0
                st.spec_k = 2
                st.spec_ewma = 0.5
            cap = min(cap, st.spec_k)
        if cap < 1:
            return None
        return st.drafter.draft(st.cur, cap) or None

    def _note_spec(self, st: _Stream, toks: list[int],
                   done: bool) -> None:
        """Fold one spec-superpool ride into the stream's adaptive
        controller and the serving counters/SLO plane.  An EOS finish
        scores 1.0 — the chain was cut by the stream, not by a draft
        miss — so a stream that dies mid-draft never punishes the
        drafter."""
        drafted = st.k - 1
        accepted = len(toks) - 1
        rate = 1.0 if done else accepted / max(1, drafted)
        st.spec_ewma = rate if st.spec_ewma < 0.0 else \
            0.5 * st.spec_ewma + 0.5 * rate
        prior = self._spec_prior.get(st.tenant)
        self._spec_prior[st.tenant] = rate if prior is None else \
            0.5 * prior + 0.5 * rate
        adaptive = bool(_params.get("llm_spec_adaptive"))
        spec_cap = max(0, int(_params.get("llm_spec_k")))
        if adaptive:
            # the live-adaptation shape the autotuning ROADMAP item
            # wants: double toward the cap while drafts land, halve to
            # (eventually) 0 = the non-speculative fallback while they
            # miss — convergence to either extreme takes ~3 pools
            if st.spec_ewma >= 0.6:
                st.spec_k = min(spec_cap, max(2, st.spec_k * 2))
            elif st.spec_ewma < 0.35:
                st.spec_k //= 2
        st.ticket.spec_k = st.spec_k if adaptive else spec_cap
        st.ticket.spec_accept_ewma = round(st.spec_ewma, 4)
        with self._lock:
            self.spec_submits += 1
            self.spec_tokens += len(toks)
            self.spec_drafted += drafted
            self.spec_drafts_accepted += accepted
        if self._slo is not None:
            # the PR-10 SLO plane's per-tenant speculative pair: how
            # often drafts land, and the tokens one submit yields —
            # read live via RuntimeServer.metrics() next to the
            # inter-token quantiles speculation is supposed to move
            self._slo.observe(st.tenant, "spec_accept_rate", rate)
            self._slo.observe(st.tenant, "spec_tokens_per_submit",
                              len(toks))

    def _collect_stream(self, st: _Stream, dt: float) -> bool:
        """Read ONE stream's tokens off its completed superpool and fold
        them into the ticket/ledger/SLO state; returns whether the
        stream finished (EOS or budget).  Speculative streams read the
        accepted prefix and roll their rejected tail back; plain
        streams read the TOK chain."""
        if st.spec:
            # only the accepted prefix surfaces — the SVERIFY body
            # killed the chain at the first draft mismatch (or a live
            # EOS) in-graph
            toks, done = read_spec_batched(self.VOUT, st.seq)
            # every position's k/v was staged into the tail slots at
            # seed time; the ledger advances by the FULL position
            # count, then the rejected tail rolls back (version-jump
            # truncation) so no stale KV survives into the next
            # superpool.  QS/LIM/DTOKS tiles are rewritten by the next
            # seed — they release with the stream, not per iteration
            self.kv.note_appended(st.seq, st.k)
            rejected = st.k - len(toks)
            if rejected:
                self.kv.rollback_tail(
                    st.seq, self.kv.seq_len(st.seq) - rejected)
            self._note_spec(st, toks, done)
        else:
            # tokens past a mid-superpool EOS are the predicated tail —
            # read_token_chain never surfaces them
            toks, done = read_token_chain(self.TOK, st.seq, st.k)
            for t_i in range(st.k):
                self.TOK.discard(st.seq, t_i)
            # the ledger advances by the FULL k: the OUT bodies
            # appended every step's k/v (predication holds tokens, not
            # appends), and a done stream's pages free anyway
            self.kv.note_appended(st.seq, st.k)
        if st.drafter is not None:
            # keep the table aligned with the true history whatever
            # mode this iteration ran, so spec can re-engage any time
            # (never-speculated streams catch up lazily in _spec_draft)
            for t_i in toks:
                st.drafter.observe(t_i)
        st.cur = toks[-1]
        if toks and not st.ticket.tokens:
            # the stream's first token closes its TTFT (the stamp is
            # what the bench prefix sweep quantiles)
            st.ticket.first_token_at = time.monotonic()
            if self._slo is not None:
                self._slo.observe(
                    st.tenant, "ttft_ms",
                    (st.ticket.first_token_at
                     - st.ticket.submitted_at) * 1e3)
        if toks:
            # every token samples the inter-token latency (this
            # iteration's wall amortized over its k tokens)
            tok_ms = dt / len(toks) * 1e3
            if self._slo is not None:
                for _ in toks:
                    self._slo.observe(st.tenant, "tok_latency_ms", tok_ms)
            ctl = self._k_ctl.get(st.tenant)
            if ctl is not None:
                # the adaptive plane folds the same signal; a converged
                # adoption persists to the tuning DB exactly once
                ctl.observe(tok_ms)
                wb = ctl.take_writeback()
                if wb is not None:
                    from ..tune import adaptive as _adaptive
                    _adaptive.writeback(st.tenant, wb,
                                        ctl.ewma_of(wb) or tok_ms)
        with self._lock:
            st.ticket.tokens.extend(toks)
            st.ticket.per_token_s.extend([dt] * len(toks))
            self.tokens_generated += len(toks)
        return done or len(st.ticket.tokens) >= st.max_new

    def _decode_step(self, live: list[_Stream]) -> None:
        """One continuous-batching iteration: ONE decode superpool per
        (tenant, mode) over its live streams — speculative draft-k-
        verify pools for streams whose drafter has a proposal (ISSUE
        12), the PR-9 k-step SAMPLE superpool for the rest, with k =
        ``llm_steps_per_pool`` clipped to each stream's remaining
        budget.  Sampling/verification runs in-graph, so the host reads
        a whole pool's tokens off the TOK/STOK chain tiles per submit;
        a spec stream's rejected tail is rolled back
        (``rollback_tail``) before its next pool.  Failures are
        contained per stream (slot allocation) or per tenant+mode (pool
        shed/failure) — the rest of the batch decodes on."""
        k_max = max(1, int(_params.get("llm_steps_per_pool")))
        spec_cap = max(0, int(_params.get("llm_spec_k")))
        spec_adaptive = bool(_params.get("llm_spec_adaptive"))
        if _params.get("llm_prefetch_ahead"):
            # the tier return path, ahead of the decode wavefront: pages
            # the PREVIOUS iteration's eviction pressure pushed to the
            # host tier stage back in asynchronously while this thread
            # does host-side prep (slot preallocation, seeding, pool
            # build) — an HBM budget below the working set costs
            # overlapped bandwidth instead of synchronous stage-in
            # stalls when the superpool dispatches.  Advisory: a
            # prefetch failure must never fail the batch (on-demand
            # stage-in still serves every page).
            try:
                n = self.tiers.prefetch_seqs([st.seq for st in live])
            except Exception:                # noqa: BLE001 — contain
                n = 0
            if n and self._slo is not None:
                self._slo.inc("_server", "kv_prefetched_pages", n)
        ready: list[_Stream] = []
        for st in live:
            draft = self._spec_draft(st, spec_cap, spec_adaptive)
            try:
                if draft is not None:
                    st.k = 1 + len(draft)
                    st.spec = True
                    # preallocate FIRST: the staged speculative slots
                    # must be private (CoW tails privatize here) before
                    # the seed writes the draft chain's k/v into them
                    preallocate_decode_steps(self.kv, st.seq, st.k)
                    seed_spec_batched(self.model, self.kv, self.QS,
                                      self.LIM, self.DTOKS, st.seq,
                                      st.cur, draft, spec_cap + 1,
                                      eos=st.eos)
                else:
                    st.k = max(1, min(self._tenant_k(st.tenant, k_max),
                                      st.max_new - len(st.ticket.tokens)))
                    st.spec = False
                    preallocate_decode_steps(self.kv, st.seq, st.k)
                    seed_stream_step(self.model, self.Q, self.TOK,
                                     st.seq, st.cur, eos=st.eos)
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed([st], e)
                continue
            ready.append(st)
        # one pool per (tenant, mode): spec and plain streams of a
        # tenant ride SEPARATE superpools in the same iteration (the
        # two graphs differ structurally; WFQ still arbitrates both
        # under the tenant's weight)
        by_group: dict[tuple[str, bool], list[_Stream]] = {}
        for st in ready:
            by_group.setdefault((st.tenant, st.spec), []).append(st)
        t0 = time.perf_counter()
        submitted: list[tuple[Any, Any, list[_Stream]]] = []
        for (tenant, spec), group in by_group.items():
            try:
                if spec:
                    tp = spec_batched_ptg(
                        self.kv, self.QS, self.LIM, self.DTOKS,
                        self.VOUT, self.EMB, [st.seq for st in group],
                        [st.k for st in group], pad=spec_cap + 1,
                        devices=self.devices,
                        name=f"llm_spec{next(self._pool_seq)}")
                else:
                    tp = decode_superpool_ptg(
                        self.kv, self.Q, self.O, self.TOK, self.EMB,
                        [st.seq for st in group], [st.k for st in group],
                        devices=self.devices,
                        name=f"llm_decode{next(self._pool_seq)}")
                tp = self._maybe_lower_regions(tp)
                submitted.append((self._server.submit(
                    tp, tenant=tenant,
                    priority=max(st.priority for st in group),
                    compiled=bool(_params.get("llm_compiled_pools"))),
                    tp, group))
                with self._lock:
                    self.decode_submits += 1
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed(group, e)
        finished: list[_Stream] = []
        for tk, tp, group in submitted:
            try:
                tk.result(timeout=_params.get("llm_step_timeout"))
            except BaseException as e:       # noqa: BLE001 — contain
                # the pool may still be running past its timeout: page
                # release rides its completion, not this failure
                self._retire_failed(group, e, defer_pool=tp)
                continue
            dt = time.perf_counter() - t0
            for st in group:
                try:
                    if self._collect_stream(st, dt):
                        finished.append(st)
                except BaseException as e:   # noqa: BLE001 — contain
                    # one stream's result/rollback failure (e.g. a
                    # rolled-back page spilled beyond the host tier)
                    # must fail THAT stream, not the batcher
                    self._retire_failed([st], e)
        with self._lock:
            self.steps += 1
            for st in finished:
                self._live.remove(st)
                self.streams_completed += 1
        for st in finished:
            if self.prefix is not None:
                # donate the prompt pages BEFORE free_seq: the trie's
                # retained fork (refcount++) is what keeps them out of
                # the recycle path.  Only cleanly-finished streams
                # donate — a failed stream's pages may be zombie-written
                # (and never reach this loop).  Donation is an
                # optimization: its failure must never fail the stream.
                try:
                    self.prefix.donate(st.seq, st.prompt)
                except Exception:        # noqa: BLE001 — contain
                    pass
            self._release_stream_state(st.seq)
            st.ticket._resolve()
