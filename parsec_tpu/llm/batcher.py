"""Continuous batching: the LLM session layer over a RuntimeServer.

Orca-style iteration-level scheduling on the serving layer's own
primitives: clients open *streams* (:meth:`ContinuousBatcher
.submit_stream` — surfaced as ``RuntimeServer.submit_stream``), and one
batcher thread runs the decode loop::

    each iteration:
      admit newly-arrived streams   -> prefill pools (PF tasks)
      group live streams by tenant  -> ONE decode-step pool per tenant
      submit all pools concurrently -> server.submit(tenant=...)
      await tickets, read O, sample -> next token per stream
      retire finished streams       -> kv.free_seq (pages recycle)

New streams join at the next iteration boundary and finished streams
leave without stalling the batch — continuous batching, with the
runtime's admission control bounding the in-flight pools and the WFQ
fair scheduler arbitrating decode pools against each other and against
whatever dense-linear-algebra tenants share the server (the soak test
mixes decode with a Cholesky pool, ``tests/test_llm_serve.py``).

Every decode-step pool is a fresh PTG taskpool: the live re-enqueue
path PR 3 built (``Context.add_taskpool`` under ``_submit_lock``) runs
once per token batch, and terminated pools retire from the process
registry (``runtime/taskpool.py``) so a million-token serving run's
footprint stays bounded by LIVE streams, not by history.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Sequence

import numpy as np

from ..core.future import Future
from ..core.params import params as _params
from ..data.datatype import TileType
from ..data_dist.collection import DictCollection
from ..data_dist.paged_kv import PagedKVCollection
from .decode import decode_step_ptg, prefill_chunks, prefill_ptg
from .model import ToyLM

_params.register("llm_page_size", 16,
                 "tokens per KV page (PagedKVCollection block size)")
_params.register("llm_max_batch", 32,
                 "live decode streams a batcher serves concurrently; "
                 "arrivals beyond it queue for the next free slot")
_params.register("llm_max_pages", 4096,
                 "physical KV pages the batcher's cache may hold")
_params.register("llm_step_timeout", 60.0,
                 "seconds the batcher waits for one decode-step pool "
                 "before failing the streams riding it")


class StreamTicket:
    """One generation stream's handle.  ``tokens`` grows live — snapshot
    with :meth:`generated`; ``result()`` blocks for the finished
    transcript."""

    def __init__(self, name: str, tenant: str) -> None:
        self.name = name
        self.tenant = tenant
        self.state = "queued"
        self.submitted_at = time.monotonic()
        self.tokens: list[int] = []
        self.per_token_s: list[float] = []
        self.prefill_s: float | None = None
        self._future: Future = Future()

    def generated(self) -> list[int]:
        """Snapshot of the tokens generated so far (the batcher appends
        concurrently; ``list()`` of a list is atomic under the GIL)."""
        return list(self.tokens)

    def result(self, timeout: float | None = None) -> dict:
        """Block for completion; returns ``{"tokens": [...],
        "per_token_s": [...], "prefill_s": ...}``."""
        kind, v = self._future.get(timeout)
        if kind == "err":
            raise v
        return v

    def done(self) -> bool:
        return self._future.is_ready()

    def _resolve(self) -> None:
        self.state = "done"
        self._future.set(("ok", {"tokens": list(self.tokens),
                                 "per_token_s": list(self.per_token_s),
                                 "prefill_s": self.prefill_s}))

    def _fail(self, e: BaseException) -> None:
        self.state = "failed"
        self._future.set(("err", e))


class _Stream:
    __slots__ = ("seq", "tenant", "priority", "prompt", "max_new",
                 "ticket", "cur", "devices")

    def __init__(self, seq: Any, tenant: str, priority: int,
                 prompt: Sequence[int], max_new: int,
                 ticket: StreamTicket) -> None:
        self.seq = seq
        self.tenant = tenant
        self.priority = priority
        self.prompt = list(prompt)
        self.max_new = max_new
        self.ticket = ticket
        self.cur = int(prompt[-1])


class ContinuousBatcher:
    """The decode loop.  Owns the paged KV cache plus the Q/O side
    collections; rides an existing :class:`RuntimeServer` for admission,
    fairness, and the hot context."""

    def __init__(self, server: Any, model: ToyLM | None = None,
                 kv: PagedKVCollection | None = None,
                 max_batch: int | None = None,
                 devices: str = "cpu") -> None:
        self._server = server
        self.model = model or ToyLM()
        H, D = self.model.num_heads, self.model.head_dim
        self.kv = kv or PagedKVCollection(
            "llmKV", page_size=_params.get("llm_page_size"),
            num_heads=H, head_dim=D,
            max_pages=_params.get("llm_max_pages"))
        assert (self.kv.num_heads, self.kv.head_dim) == (H, D), \
            "model and KV cache disagree on head geometry"
        self.Q = DictCollection("llmQ", dtt=TileType((3, H, D), np.float32))
        self.O = DictCollection("llmO", dtt=TileType((H, D), np.float32))
        self.max_batch = max_batch or _params.get("llm_max_batch")
        self.devices = devices
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._pending: deque[_Stream] = deque()
        self._live: list[_Stream] = []
        self._seq_ids = itertools.count()
        self._stop = False
        self._abort: BaseException | None = None
        self.steps = 0
        self.tokens_generated = 0
        self.streams_completed = 0
        self._pool_seq = itertools.count()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-batcher")
        self._thread.start()

    # -- client API ------------------------------------------------------
    def submit_stream(self, prompt_tokens: Sequence[int],
                      max_new_tokens: int = 16, tenant: str = "default",
                      priority: int = 0) -> StreamTicket:
        """Open one generation stream; it joins the running batch at the
        next iteration boundary."""
        if not prompt_tokens:
            raise ValueError("prompt_tokens must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        seq = next(self._seq_ids)
        ticket = StreamTicket(f"stream{seq}", tenant)
        st = _Stream(seq, tenant, priority, prompt_tokens,
                     max_new_tokens, ticket)
        with self._lock:
            if self._stop:
                # typed shed, same contract as server.submit: clients
                # catching AdmissionRejected to back off keep working
                # through the drain window
                from ..serve.admission import AdmissionRejected
                raise AdmissionRejected("llm batcher is stopped")
            self._pending.append(st)
        self._wake.set()
        return ticket

    def stats(self) -> dict:
        with self._lock:
            return {
                "live_streams": len(self._live),
                "queued_streams": len(self._pending),
                "steps": self.steps,
                "tokens_generated": self.tokens_generated,
                "streams_completed": self.streams_completed,
                "kv": self.kv.stats(),
            }

    def stop(self, timeout: float | None = 60.0) -> None:
        """Graceful: no new streams, finish the live ones, join.  On
        timeout the loop is aborted and leftover streams fail."""
        with self._lock:
            self._stop = True
        self._wake.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            self._abort = RuntimeError("batcher stop timed out")
            self._wake.set()
            self._thread.join(5.0)

    # -- the iteration loop ---------------------------------------------
    def _loop(self) -> None:
        try:
            while True:
                if self._abort is not None:
                    # checked BEFORE popping arrivals: _fail_all covers
                    # _live + _pending, so anything popped here would
                    # slip through with an unresolved ticket
                    self._fail_all(self._abort)
                    return
                with self._lock:
                    room = self.max_batch - len(self._live)
                    fresh = [self._pending.popleft()
                             for _ in range(min(room, len(self._pending)))]
                    live = list(self._live)
                    stopping = self._stop
                if not fresh and not live:
                    if stopping:
                        return
                    self._wake.wait(0.05)
                    self._wake.clear()
                    continue
                if fresh:
                    ok = self._prefill(fresh)
                    with self._lock:
                        self._live.extend(ok)
                        live = list(self._live)
                if live:
                    self._decode_step(live)
        except BaseException as e:      # noqa: BLE001 — fail the streams,
            self._fail_all(e)           # never leave clients blocked

    def _retire_failed(self, streams: list[_Stream], e: BaseException,
                       defer_pool: Any = None) -> None:
        """Contain a failure to the streams it actually hit: one tenant's
        shed pool (admission timeout), one stream's exhausted page budget
        — the OTHER tenants' streams keep decoding.

        ``defer_pool`` must be passed when the streams' pool may STILL BE
        RUNNING (a step-timeout: serve tickets cannot cancel a live DAG):
        freeing the KV pages immediately would hand them to a new stream
        while the zombie pool's OUT tasks can still write into them —
        the pages release only when that pool actually terminates (the
        listener fires immediately if it already has)."""
        with self._lock:
            for st in streams:
                if st in self._live:
                    self._live.remove(st)
        seqs = [st.seq for st in streams]
        for st in streams:
            st.ticket._fail(e)
        if defer_pool is None:
            for s in seqs:
                self._release_stream_state(s)
        else:
            defer_pool.add_completion_listener(
                lambda _tp: [self._release_stream_state(s) for s in seqs])

    def _release_stream_state(self, seq: Any) -> None:
        """Everything a retired sequence held: KV pages back to the free
        list, its Q/O side tiles dropped — the serving footprint must be
        bounded by LIVE streams, not by every stream ever served.  Safe
        for a never-allocated seq (all no-ops)."""
        self.kv.free_seq(seq)
        self.Q.discard(seq)
        self.O.discard(seq)

    def _fail_all(self, e: BaseException) -> None:
        with self._lock:
            victims = self._live + list(self._pending)
            self._live = []
            self._pending.clear()
        for st in victims:
            st.ticket._fail(e)
            self._release_stream_state(st.seq)

    def _prefill(self, fresh: list[_Stream]) -> list[_Stream]:
        """Write the new streams' prompt K/V into fresh pages, grouped
        into one PF pool per tenant.  Returns the streams that made it —
        an exhausted page budget fails ONE stream, a shed pool fails ONE
        tenant's arrivals, never the whole batch."""
        stream_chunks: dict[Any, dict[tuple, np.ndarray]] = {}
        by_tenant: dict[str, list[_Stream]] = {}
        for st in fresh:
            try:
                self.kv.alloc_seq(st.seq)
                stream_chunks[st.seq] = prefill_chunks(
                    self.model, self.kv, st.seq, st.prompt[:-1])
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed([st], e)
                continue
            st.ticket.state = "prefill"
            by_tenant.setdefault(st.tenant, []).append(st)
        t0 = time.perf_counter()
        tickets: list[tuple[Any, Any, list[_Stream]]] = []
        ok: list[_Stream] = []
        for tenant, group in by_tenant.items():
            seqs = [st.seq for st in group if self.kv.npages(st.seq) > 0]
            if not seqs:
                ok.extend(group)  # single-token prompts cache nothing
                continue
            # THIS group's chunks only: the T key space is what lowering
            # and operators may walk, so it must not declare other
            # tenants' (or failed streams') tiles
            chunks: dict[tuple, np.ndarray] = {}
            for st in group:
                chunks.update(stream_chunks.get(st.seq, {}))
            try:
                T = DictCollection(
                    f"llmT{next(self._pool_seq)}",
                    dtt=self.kv.default_dtt,
                    init_fn=lambda *k, _c=chunks: _c[k],
                    keys=list(chunks))
                tp = prefill_ptg(self.kv, T, seqs, devices=self.devices,
                                 name=f"llm_prefill{next(self._pool_seq)}")
                tickets.append((self._server.submit(
                    tp, tenant=tenant,
                    priority=max(st.priority for st in group)), tp, group))
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed(group, e)
        for tk, tp, group in tickets:
            try:
                tk.result(timeout=_params.get("llm_step_timeout"))
            except BaseException as e:       # noqa: BLE001 — contain
                # the pool may still be running past its timeout: page
                # release rides its completion, not this failure
                self._retire_failed(group, e, defer_pool=tp)
                continue
            ok.extend(group)
        dt = time.perf_counter() - t0
        for st in ok:
            st.ticket.prefill_s = dt
            st.ticket.state = "decoding"
        return ok

    def _decode_step(self, live: list[_Stream]) -> None:
        """One continuous-batching iteration over every live stream.
        Failures are contained per stream (slot allocation) or per
        tenant (pool shed/failure) — the rest of the batch decodes on."""
        ready: list[_Stream] = []
        for st in live:
            try:
                self.kv.ensure_tail_slot(st.seq)
                q = self.Q.data_of(st.seq).get_copy(0)
                q.value = self.model.q3(st.cur)
                q.version += 1
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed([st], e)
                continue
            ready.append(st)
        by_tenant: dict[str, list[_Stream]] = {}
        for st in ready:
            by_tenant.setdefault(st.tenant, []).append(st)
        t0 = time.perf_counter()
        submitted: list[tuple[Any, Any, list[_Stream]]] = []
        for tenant, group in by_tenant.items():
            try:
                tp = decode_step_ptg(
                    self.kv, self.Q, self.O, [st.seq for st in group],
                    devices=self.devices,
                    name=f"llm_decode{next(self._pool_seq)}")
                submitted.append((self._server.submit(
                    tp, tenant=tenant,
                    priority=max(st.priority for st in group)), tp, group))
            except BaseException as e:       # noqa: BLE001 — contain
                self._retire_failed(group, e)
        finished: list[_Stream] = []
        for tk, tp, group in submitted:
            try:
                tk.result(timeout=_params.get("llm_step_timeout"))
            except BaseException as e:       # noqa: BLE001 — contain
                # the pool may still be running past its timeout: page
                # release rides its completion, not this failure
                self._retire_failed(group, e, defer_pool=tp)
                continue
            dt = time.perf_counter() - t0
            for st in group:
                o = np.asarray(
                    self.O.data_of(st.seq).newest_copy().value)
                st.cur = self.model.sample(o)
                self.kv.note_appended(st.seq)
                with self._lock:
                    st.ticket.tokens.append(st.cur)
                    st.ticket.per_token_s.append(dt)
                    self.tokens_generated += 1
                if len(st.ticket.tokens) >= st.max_new:
                    finished.append(st)
        with self._lock:
            self.steps += 1
            for st in finished:
                self._live.remove(st)
                self.streams_completed += 1
        for st in finished:
            self._release_stream_state(st.seq)
            st.ticket._resolve()
