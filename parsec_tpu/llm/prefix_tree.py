"""The automatic prefix cache: a radix tree over prompt-token runs.

ISSUE 11's tentpole, and the automation of the ``fork_from=`` seam
PR 9 surfaced: ``PagedKVCollection.fork`` already shares prompt pages
refcounted copy-on-write, but only when the CALLER hand-wires which
earlier stream to fork.  The :class:`PrefixTree` makes the sharing
global and automatic — the million-user shape is thousands of requests
carrying the same system prompt, and none of them should re-run its
prefill.

Anatomy:

- **Page-granular radix tree.**  Every edge is one page worth of tokens
  (a ``page_size``-tuple); a node at depth ``d`` names a ``d``-page
  token prefix.  Matching an incoming prompt walks child edges keyed by
  the prompt's successive page runs, so lookup is O(prompt pages), and
  a hit can only ever cover FULL pages — a partial page in the cache
  holds k/v of tokens past the divergence point, so "hit mid page"
  rounds DOWN to the last whole page and the tail (partial page
  included) prefills normally.

- **Donation, not retention-by-accident.**  When a stream retires
  cleanly, the batcher *donates* its prompt pages: the trie forks the
  full prompt-covering pages into a retained synthetic sequence
  (:meth:`PagedKVCollection.fork_prefix` — refcount++, no bytes move)
  BEFORE ``free_seq`` recycles the stream's own references.  Retained
  pages are ordinary refcounted pages: a later adopter forks from the
  retained sequence the same way, and eviction is just ``free_seq`` of
  the retained id (pages still shared by live adopters survive on
  their refcounts).

- **LRU + byte budget.**  Retained entries carry a nominal byte weight
  (``pages * page_bytes`` — physical sharing between entries is not
  discounted, so the budget is conservative) and an LRU clock touched
  on every donation and adoption hit; :meth:`donate` evicts from the
  cold end until the tree fits ``llm_prefix_budget_bytes``.

- **Eviction-aware pinning.**  ``adopt`` resolves match → ``fork_prefix``
  under the tree lock, so an entry can never be evicted between being
  matched and being forked; once the fork exists, eviction of the donor
  only drops refcounts the child does not depend on.

Thread-safety: one RLock; the lock order is tree → collection
(``PagedKVCollection._lock``), and the collection never calls back into
the tree.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from typing import Any, Sequence

from ..core.params import params as _params
from ..data_dist.paged_kv import PagedKVCollection

_params.register("llm_prefix_cache", False,
                 "automatic prefix cache: match every incoming prompt "
                 "against a global radix tree of retained prompt pages "
                 "and fork the longest full-page prefix copy-on-write "
                 "instead of re-prefilling it (docs/LLM.md)")
_params.register("llm_prefix_budget_bytes", 64 << 20,
                 "byte budget for trie-retained prefix pages (nominal: "
                 "pages * page_bytes per entry); LRU entries evict past "
                 "it and their pages recycle unless still CoW-shared")

_entry_ids = itertools.count()

# concurrency contracts, enforced by analysis.runtimelint (docs/ANALYSIS.md):
# every tree structure (the trie's node children/entry lists, the LRU
# ring, the byte gauge and hit/miss counters) mutates only under the
# tree's RLock — match() on the serving hot path races donate()/evict()
# from batcher drains; the ``*_locked`` helpers document the lock they
# inherit.  _Entry fields are single-writer (built before publication,
# touch stamped under the same lock).
_LOCK_PROTECTED = {
    "_Node.children": "_lock",
    "_Node.entries": "_lock",
    "PrefixTree._lru": "_lock",
    "PrefixTree._clock": "_lock",
    "PrefixTree._bytes": "_lock",
    "PrefixTree.hits": "_lock",
    "PrefixTree.misses": "_lock",
    "PrefixTree.donations": "_lock",
    "PrefixTree.evictions": "_lock",
}
_LOCK_ORDER = ("_lock",)


class _Entry:
    """One retained prefix: a synthetic sequence in the KV collection
    whose first ``pages`` pages hold k/v of exactly ``tokens``."""

    __slots__ = ("seq", "tokens", "pages", "nbytes", "path", "touch")

    def __init__(self, seq: Any, tokens: tuple, pages: int,
                 nbytes: int) -> None:
        self.seq = seq
        self.tokens = tokens
        self.pages = pages
        self.nbytes = nbytes
        self.path: list[_Node] = []      # nodes depth 1..pages
        self.touch = 0                   # LRU clock stamp (tree._clock)

    def __repr__(self) -> str:
        return f"<prefix {self.seq} pages={self.pages}>"


class _Node:
    __slots__ = ("children", "entries")

    def __init__(self) -> None:
        self.children: dict[tuple, _Node] = {}
        self.entries: list[_Entry] = []


class PrefixTree:
    """Radix tree of retained prompt-page runs over one
    :class:`PagedKVCollection` (see module docstring)."""

    def __init__(self, kv: PagedKVCollection,
                 budget_bytes: int | None = None) -> None:
        self.kv = kv
        self.budget_bytes = (_params.get("llm_prefix_budget_bytes")
                             if budget_bytes is None else int(budget_bytes))
        self._lock = threading.RLock()
        self._root = _Node()
        # LRU over retained entries: cold end first.  Touched on donate
        # and on every adoption hit.
        self._lru: "OrderedDict[Any, _Entry]" = OrderedDict()
        self._clock = 0          # monotonic touch stamps (O(1) _pick)
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.donations = 0
        self.evictions = 0

    # -- matching ---------------------------------------------------------
    def _runs(self, tokens: Sequence[int]):
        P = self.kv.page_size
        for d in range(len(tokens) // P):
            yield tuple(int(t) for t in tokens[d * P:(d + 1) * P])

    def _descend(self, tokens: Sequence[int]) -> tuple["_Node", int]:
        """Deepest node reachable along ``tokens``' page runs (depth in
        pages).  Callers hold the lock."""
        node, depth = self._root, 0
        for run in self._runs(tokens):
            child = node.children.get(run)
            if child is None:
                break
            node, depth = child, depth + 1
        return node, depth

    def match(self, tokens: Sequence[int]) -> tuple[Any, int]:
        """Longest retained full-page prefix of ``tokens``: returns
        ``(retained seq, pages)`` or ``(None, 0)``.  Pure lookup — use
        :meth:`adopt` to actually fork (match + fork are atomic there)."""
        with self._lock:
            node, depth = self._descend(tokens)
            while depth > 0 and not node.entries:
                # an interior node whose entries all evicted: back up
                node, depth = self._descend(tokens[:(depth - 1)
                                                   * self.kv.page_size])
            if depth == 0 or not node.entries:
                return None, 0
            return self._pick(node).seq, depth

    def _pick(self, node: "_Node") -> _Entry:
        """Of the entries passing through a node, fork from the most
        recently used (highest touch stamp) — matches the LRU's idea of
        who stays warm, in O(entries at this node)."""
        return max(node.entries, key=lambda e: e.touch)

    def _touch_locked(self, entry: _Entry) -> None:  # lint: holds(_lock)
        self._clock += 1
        entry.touch = self._clock
        if entry.seq in self._lru:
            self._lru.move_to_end(entry.seq)

    # -- adoption (match + CoW fork, atomic) ------------------------------
    def adopt(self, child_seq: Any, tokens: Sequence[int]) -> int:
        """Materialize ``child_seq`` in the collection, sharing the
        longest retained full-page prefix of ``tokens`` copy-on-write.
        Returns the number of pages reused (0 = miss; the child is then
        a plain empty sequence).  ``tokens`` are the CACHEABLE tokens —
        the batcher passes ``prompt[:-1]``, the run prefill would cache.

        Match and fork happen under the tree lock: a matched entry
        cannot be evicted before its pages are shared (the
        eviction-aware pin), and after the fork the child's own
        refcounts keep the shared pages alive whatever the LRU does."""
        with self._lock:
            node, depth = self._descend(tokens)
            while depth > 0 and not node.entries:
                node, depth = self._descend(tokens[:(depth - 1)
                                                   * self.kv.page_size])
            if depth == 0 or not node.entries:
                self.misses += 1
                self.kv.alloc_seq(child_seq)
                return 0
            e = self._pick(node)
            self.kv.fork_prefix(e.seq, child_seq, depth)
            self._touch_locked(e)
            self.hits += 1
            self.kv.prefix_hits += 1
            self.kv.prefix_pages_reused += depth
            return depth

    # -- donation ---------------------------------------------------------
    def donate(self, seq: Any, prompt: Sequence[int]) -> Any | None:
        """Retain ``seq``'s prompt pages before it is freed: the pages
        fully covered by ``prompt[:-1]`` (the cacheable run — decode
        never wrote them) fork into a synthetic retained sequence.
        Idempotent per path: if a live entry already covers this exact
        prefix at full depth, it is touched instead of duplicated.
        Returns the retained seq id, or None when nothing was retained
        (short prompt, duplicate path, or a zero budget)."""
        P = self.kv.page_size
        cacheable = len(prompt) - 1
        pages = cacheable // P
        if pages <= 0 or self.budget_bytes <= 0:
            return None
        tokens = tuple(int(t) for t in prompt[:pages * P])
        nbytes = pages * self.kv.page_bytes
        with self._lock:
            node, depth = self._descend(tokens)
            if depth == pages and any(e.pages >= pages
                                      for e in node.entries):
                # this exact prefix is already retained: refresh it
                for e in node.entries:
                    if e.pages >= pages and e.seq in self._lru:
                        self._touch_locked(e)
                        break
                return None
            retained = ("~prefix", next(_entry_ids))
            self.kv.fork_prefix(seq, retained, pages)
            entry = _Entry(retained, tokens, pages, nbytes)
            node = self._root
            for run in self._runs(tokens):
                node = node.children.setdefault(run, _Node())
                node.entries.append(entry)
                entry.path.append(node)
            self._lru[retained] = entry
            self._touch_locked(entry)
            self._bytes += nbytes
            self.donations += 1
            self._evict_over_budget_locked()
            return retained

    # -- eviction ---------------------------------------------------------
    def _evict_over_budget_locked(self) -> None:  # lint: holds(_lock)
        while self._bytes > self.budget_bytes and len(self._lru) > 1:
            self._evict_one_locked()

    def _evict_one_locked(self) -> bool:  # lint: holds(_lock)
        if not self._lru:
            return False
        seq, entry = self._lru.popitem(last=False)   # coldest first
        self._bytes -= entry.nbytes
        for node in entry.path:
            try:
                node.entries.remove(entry)
            except ValueError:
                pass
        # prune now-empty leaves bottom-up so the tree stays O(live)
        for d in range(len(entry.path), 0, -1):
            node = entry.path[d - 1]
            if node.entries or node.children:
                break
            parent = entry.path[d - 2] if d > 1 else self._root
            run = tuple(entry.tokens[(d - 1) * self.kv.page_size:
                                     d * self.kv.page_size])
            parent.children.pop(run, None)
        self.kv.free_seq(seq)
        self.evictions += 1
        return True

    def evict(self, n: int = 1) -> int:
        """Force-evict up to ``n`` cold entries (tests / pressure)."""
        done = 0
        with self._lock:
            for _ in range(n):
                if not self._evict_one_locked():
                    break
                done += 1
        return done

    def clear(self) -> None:
        with self._lock:
            while self._evict_one_locked():
                pass

    # -- introspection ----------------------------------------------------
    def live_entries(self) -> dict:
        """``{retained seq: (tokens, pages)}`` — the oracle surface the
        property tests compare against a brute-force LCP scan."""
        with self._lock:
            return {seq: (e.tokens, e.pages)
                    for seq, e in self._lru.items()}

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._lru),
                "retained_pages": sum(e.pages for e in self._lru.values()),
                "retained_bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "donations": self.donations,
                "evictions": self.evictions,
            }
