"""LLM inference serving on the runtime's own primitives: a paged KV
cache as a :class:`~parsec_tpu.data_dist.paged_kv.PagedKVCollection`,
ragged prefill/decode task classes (:mod:`parsec_tpu.llm.decode`),
speculative draft-k-verify superpools (ISSUE 12), and continuous
batching over a :class:`~parsec_tpu.serve.RuntimeServer`
(:mod:`parsec_tpu.llm.batcher`).  See ``docs/LLM.md``."""

from ..data_dist.paged_kv import PagedKVCollection
from .batcher import ContinuousBatcher, StreamTicket
from .decode import (decode_step_ptg, decode_superpool_ptg,
                     preallocate_decode_steps, prefill_chunks, prefill_ptg,
                     read_spec_batched, read_spec_chain, read_token_chain,
                     seed_decode_superpool, seed_spec_batched,
                     seed_spec_batched_pool, seed_spec_stream,
                     seed_spec_superpool, spec_batched_ptg,
                     spec_superpool_ptg)
from .model import NgramDrafter, ToyLM

__all__ = ["PagedKVCollection", "ToyLM", "NgramDrafter",
           "ContinuousBatcher", "StreamTicket", "decode_step_ptg",
           "decode_superpool_ptg", "preallocate_decode_steps",
           "prefill_ptg", "prefill_chunks", "read_token_chain",
           "read_spec_chain", "read_spec_batched",
           "seed_decode_superpool", "seed_spec_stream",
           "seed_spec_batched", "seed_spec_batched_pool",
           "seed_spec_superpool", "spec_batched_ptg",
           "spec_superpool_ptg"]
