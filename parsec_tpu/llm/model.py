"""The toy language model the serving scenario generates with.

The serving subsystem exercises runtime machinery — paged KV residency,
ragged task graphs, continuous batching, multi-tenant fairness — not
model quality, so the "model" is the smallest thing with real attention
semantics: a fixed random embedding table, single-layer multi-head
attention over the KV cache, greedy argmax sampling.  Everything is
deterministic from the seed, so :meth:`ToyLM.reference_generate` (dense
numpy, no paging, no runtime) is an exact oracle for what the paged
decode pools must produce token for token.

Decode semantics (shared by the pools and the oracle): the cache holds
K/V of every token strictly BEFORE the query token; a decode step
attends the query over the cache, samples the next token, and appends
the query token's own K/V — so prefill caches ``prompt[:-1]`` and the
first decode query is ``prompt[-1]``.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..ops.ragged_attention import ragged_attention_reference


class ToyLM:
    """One attention layer over a fixed embedding table.

    For token ``t`` with embedding ``e``: ``q = e``, ``k = roll(e, 1)``
    (shifted so scores are not a pure self-similarity peak), ``v =
    e[..., ::-1]``; logits are ``o · E^T`` over the flattened heads.
    """

    def __init__(self, vocab: int = 64, num_heads: int = 4,
                 head_dim: int = 8, seed: int = 1234) -> None:
        self.vocab = int(vocab)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        rng = np.random.default_rng(seed)
        self.emb = rng.standard_normal(
            (self.vocab, self.num_heads, self.head_dim)).astype(np.float32)

    def q3(self, token: int) -> np.ndarray:
        """The ``(3, H, D)`` q/k/v stack of one token — the Q-collection
        tile the decode pools read (``llm/decode.py``).  A fresh array,
        never a view into the cached table: the caller may hand it to a
        Data copy whose consumers write in place."""
        return self.q3_table()[int(token) % self.vocab].copy()

    def q3_table(self) -> np.ndarray:
        """The full ``(vocab, 3, H, D)`` q/k/v stack table, built once —
        the EMB tile the in-graph SAMPLE class reads (ISSUE 9): logits
        come from channel 0 (``table[:, 0] · o``) and the next step's
        query is ONE gather ``table[token]``, so the per-token roll/
        reverse transforms never run on the serving hot path."""
        t = getattr(self, "_q3_table", None)
        if t is None:
            e = self.emb
            t = np.stack([e, np.roll(e, 1, axis=-1), e[..., ::-1]],
                         axis=1).astype(np.float32)
            self._q3_table = t
        return t

    def sample(self, o: np.ndarray) -> int:
        """Greedy: argmax of ``o · E^T`` (deterministic — the serving
        tests compare token-for-token against the oracle)."""
        logits = self.emb.reshape(self.vocab, -1) @ np.asarray(
            o, np.float32).reshape(-1)
        return int(np.argmax(logits))

    def reference_generate(self, prompt: Sequence[int],
                           max_new_tokens: int,
                           eos: int | None = None) -> list[int]:
        """Dense, unpaged decode loop — the oracle the paged pools and
        the continuous batcher must match exactly.  ``eos`` stops the
        stream early: the EOS token is the last one kept (the same rule
        the in-graph SAMPLE class predicates on, ``ops/ragged_attention
        .sample_step_np``)."""
        if not prompt:
            raise ValueError("prompt must be non-empty")
        ks: list[np.ndarray] = []
        vs: list[np.ndarray] = []
        for t in prompt[:-1]:
            q3 = self.q3(t)
            ks.append(q3[1])
            vs.append(q3[2])
        cur = int(prompt[-1])
        out: list[int] = []
        for _ in range(max_new_tokens):
            q3 = self.q3(cur)
            o = ragged_attention_reference(q3[0], np.array(ks),
                                           np.array(vs))
            ks.append(q3[1])
            vs.append(q3[2])
            cur = self.sample(o)
            out.append(cur)
            if eos is not None and cur == int(eos):
                break
        return out


class NgramDrafter:
    """Last-wins bigram proposal table — the cheap drafter behind the
    speculative superpool (ISSUE 12).

    The batcher feeds every token a stream actually KEPT (prompt, then
    each surfaced token) through :meth:`observe`, and :meth:`draft`
    walks the table greedily from the current token — O(1) per observed
    token, O(k) per draft, no model math, so drafting rides the host
    prep slice of the iteration without touching the serving hot path.
    Deterministic: the same history always drafts the same chain, which
    is what keeps the acceptance-rate tests reproducible.

    Repetitive traffic (greedy ToyLM generations collapse to fixed
    points / short cycles; real serving's draftable shapes are
    templated continuations) hits 80-95% bigram accuracy; adversarial
    traffic drafts garbage — rejection costs only the rejected tail's
    tasks, and the batcher's adaptive controller shrinks ``spec_k``
    toward the non-speculative path.
    """

    __slots__ = ("_next", "_prev")

    def __init__(self) -> None:
        self._next: dict[int, int] = {}
        self._prev: int | None = None

    def observe(self, token: int) -> None:
        """Fold one kept token into the table (in stream order)."""
        token = int(token)
        if self._prev is not None:
            self._next[self._prev] = token
        self._prev = token

    def draft(self, cur: int, k: int) -> list[int]:
        """Up to ``k`` proposed continuations of ``cur`` — shorter (or
        empty) when the chain runs off the table's known transitions."""
        out: list[int] = []
        t = int(cur)
        for _ in range(max(0, k)):
            nt = self._next.get(t)
            if nt is None:
                break
            out.append(nt)
            t = nt
        return out
