"""Prefill and ragged-decode task classes over the paged KV cache.

The LLM workload expressed in the runtime's own terms (ROADMAP: "ragged
attention task class per Ragged Paged Attention", arxiv 2604.15464):
plain PTG taskpools, so graphcheck statically verifies the per-step
dataflow (edge symmetry, WAR ordering against the KV pages, page-bounds
via :meth:`PagedKVCollection.has_key`) before a single token moves.

**PF(s, c)** — prefill: copy prompt chunk ``c`` of sequence ``s`` into
its KV page.  Embarrassingly parallel across chunks and sequences.

**ATTN(s, p)** — one query against one KV page, online-softmax state
threading along the sequence's ragged page list::

    ATTN(s,0) -> ATTN(s,1) -> ... -> ATTN(s, NP[s]-1) -> OUT(s)

Page tiles are uniform ``(3, page_size, H, D)`` (the fill count rides
in the tensor — ``data_dist/paged_kv.py``), so every live sequence's
ATTN tasks are the SAME class with the SAME shapes: the TPU device
module's fused same-class dispatch (``device/tpu.py:_run_vmapped``)
batches them into one vmapped XLA call — continuous batching meets the
PR-2 batched dispatch at the kernel level.

**OUT(s)** — finalize the attention output into the O collection and
append the query token's k/v into the tail page.  The tail-page write
is ordered AFTER ``ATTN(s, NP-1)``'s read of the same page by the ACC
chain — the WAR edge graphcheck checks.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from .. import ptg
from ..data.datatype import TileType
from ..data_dist.collection import DictCollection
from ..data_dist.paged_kv import META_CH, PagedKVCollection
from ..ops import ragged_attention as ra


def prefill_ptg(kv: PagedKVCollection, T: DictCollection,
                seqs: Sequence[Any], devices: str = "cpu",
                name: str = "llm_prefill") -> ptg.PTGTaskpool:
    """PF(s, c) over every allocated page of every listed sequence.
    ``T`` holds the prompt chunk tiles, keyed ``(seq, chunk)``, in the
    same ``(3, page_size, H, D)`` layout as the pages."""
    NP = tuple(kv.npages(s) for s in seqs)
    p = ptg.PTGBuilder(name, KV=kv, T=T, SEQS=tuple(seqs), NP=NP,
                       NS=len(seqs))
    t = p.task("PF",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               c=lambda g, l: range(g.NP[l.s]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.c))
    ft = t.flow("T", ptg.READ)
    ft.input(data=("T", lambda g, l: (g.SEQS[l.s], l.c)))
    fkv = t.flow("KV", ptg.RW)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.c)))
    fkv.output(data=("KV", lambda g, l: (g.SEQS[l.s], l.c)))

    def body(es: Any, task: Any, g: Any, l: Any) -> None:
        chunk = np.asarray(task.flow_data("T").value)
        kvw = task.flow_data("KV")
        kvw.value = np.array(chunk, copy=True)
        kvw.version += 1

    t.body(body)
    if devices in ("auto", "tpu"):
        # prefill is a straight page copy; stage-in + writeback through
        # the device tier is all the work, so no dedicated TPU kernel
        pass
    return p.build()


def decode_step_ptg(kv: PagedKVCollection, Q: DictCollection,
                    O: DictCollection, seqs: Sequence[Any],
                    devices: str = "cpu",
                    name: str = "llm_decode") -> ptg.PTGTaskpool:
    """One decode iteration for every listed sequence.

    Callers must have made the write slot real first
    (:meth:`PagedKVCollection.ensure_tail_slot`), so ``NP[s] >= 1`` and
    the tail page is private — the builder snapshots the page counts.
    """
    NP = tuple(kv.npages(s) for s in seqs)
    assert all(n >= 1 for n in NP), \
        "decode needs ensure_tail_slot() first (NP >= 1)"
    H, D = kv.num_heads, kv.head_dim
    p = ptg.PTGBuilder(name, KV=kv, Q=Q, O=O, SEQS=tuple(seqs), NP=NP,
                       NS=len(seqs))

    t = p.task("ATTN",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               p=lambda g, l: range(g.NP[l.s]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.p))
    # drain long page chains first: the step's critical path
    t.priority(lambda g, l: g.NP[l.s] - l.p)
    fq = t.flow("Q", ptg.READ)
    fq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)))
    fkv = t.flow("KV", ptg.READ)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.p)))
    facc = t.flow("ACC", ptg.RW, dtt=TileType((H, D + 2), np.float32))
    facc.input(new=True, guard=lambda g, l: l.p == 0)
    facc.input(pred=("ATTN", "ACC", lambda g, l: {"s": l.s, "p": l.p - 1}),
               guard=lambda g, l: l.p > 0)
    facc.output(succ=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "p": l.p + 1}),
                guard=lambda g, l: l.p < g.NP[l.s] - 1)
    facc.output(succ=("OUT", "ACC", lambda g, l: {"s": l.s}),
                guard=lambda g, l: l.p == g.NP[l.s] - 1)

    def attn_body(es: Any, task: Any, g: Any, l: Any) -> None:
        acc = task.flow_data("ACC")
        acc.value = ra.attn_page_update_np(
            np.asarray(task.flow_data("Q").value),
            np.asarray(task.flow_data("KV").value),
            np.asarray(acc.value))
        acc.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="ragged_attn_page")
    t.body(attn_body)

    o = p.task("OUT", s=ptg.span(0, lambda g, l: g.NS - 1))
    o.affinity("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1))
    foacc = o.flow("ACC", ptg.READ)
    foacc.input(pred=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "p": g.NP[l.s] - 1}))
    foq = o.flow("Q", ptg.READ)
    foq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)))
    fkvw = o.flow("KVW", ptg.RW)
    fkvw.input(data=("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1)))
    fkvw.output(data=("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1)))
    fo = o.flow("O", ptg.WRITE, dtt=TileType((H, D), np.float32))
    fo.input(new=True)
    fo.output(data=("O", lambda g, l: (g.SEQS[l.s],)))

    def out_body(es: Any, task: Any, g: Any, l: Any) -> None:
        kvw = task.flow_data("KVW")
        oc = task.flow_data("O")
        new_page, out = ra.attn_out_np(
            np.asarray(task.flow_data("ACC").value),
            np.asarray(task.flow_data("Q").value),
            np.asarray(kvw.value))
        kvw.value = new_page
        kvw.version += 1
        oc.value = out
        oc.version += 1

    if devices in ("auto", "tpu"):
        o.body(device="tpu", dyld="ragged_attn_out")
    o.body(out_body)
    return p.build()


def prefill_chunks(model: Any, kv: PagedKVCollection, seq: Any,
                   tokens: Sequence[int]) -> dict[tuple, np.ndarray]:
    """Host-side prefill prep: allocate ``seq``'s pages for ``tokens``
    and return the ``(seq, chunk) -> tile`` map the T collection serves.
    Advances the length ledger — the PF tasks only move the bytes."""
    P = kv.page_size
    chunks: dict[tuple, np.ndarray] = {}
    n = len(tokens)
    for c in range((n + P - 1) // P):
        kv.alloc_page(seq)
        part = tokens[c * P:(c + 1) * P]
        tile = np.zeros(kv.default_dtt.shape, kv.dtype)
        for i, tok in enumerate(part):
            q3 = model.q3(tok)
            tile[0, i] = q3[1]
            tile[1, i] = q3[2]
        tile[META_CH, 0, 0, 0] = len(part)
        chunks[(seq, c)] = tile
    kv.note_appended(seq, n)
    return chunks
