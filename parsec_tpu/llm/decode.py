"""Prefill and ragged-decode task classes over the paged KV cache.

The LLM workload expressed in the runtime's own terms (ROADMAP: "ragged
attention task class per Ragged Paged Attention", arxiv 2604.15464):
plain PTG taskpools, so graphcheck statically verifies the per-step
dataflow (edge symmetry, WAR ordering against the KV pages, page-bounds
via :meth:`PagedKVCollection.has_key`) before a single token moves.

**PF(s, c)** — prefill: copy prompt chunk ``c`` of sequence ``s`` into
its KV page.  Embarrassingly parallel across chunks and sequences.

**ATTN(s, p)** — one query against one KV page, online-softmax state
threading along the sequence's ragged page list::

    ATTN(s,0) -> ATTN(s,1) -> ... -> ATTN(s, NP[s]-1) -> OUT(s)

Page tiles are uniform ``(3, page_size, H, D)`` (the fill count rides
in the tensor — ``data_dist/paged_kv.py``), so every live sequence's
ATTN tasks are the SAME class with the SAME shapes: the TPU device
module's fused same-class dispatch (``device/tpu.py:_run_vmapped``)
batches them into one vmapped XLA call — continuous batching meets the
PR-2 batched dispatch at the kernel level.

**OUT(s)** — finalize the attention output into the O collection and
append the query token's k/v into the tail page.  The tail-page write
is ordered AFTER ``ATTN(s, NP-1)``'s read of the same page by the ACC
chain — the WAR edge graphcheck checks.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from .. import ptg
from ..data.datatype import TileType
from ..data_dist.collection import DictCollection
from ..data_dist.paged_kv import META_CH, PagedKVCollection
from ..ops import ragged_attention as ra


def prefill_ptg(kv: PagedKVCollection, T: DictCollection,
                seqs: Sequence[Any], devices: str = "cpu",
                name: str = "llm_prefill",
                starts: Sequence[int] | None = None) -> ptg.PTGTaskpool:
    """PF(s, c) over every allocated page of every listed sequence.
    ``T`` holds the prompt chunk tiles, keyed ``(seq, chunk)``, in the
    same ``(3, page_size, H, D)`` layout as the pages.

    ``starts[i]`` is sequence ``i``'s first chunk to fill — the
    **tail-only prefill** shape (ISSUE 11): a stream admitted through
    the prefix cache already shares its first ``starts[i]`` pages
    copy-on-write with the trie, and the PF tasks must neither redo nor
    overwrite them.  Default 0 everywhere = the full prefill."""
    NP = tuple(kv.npages(s) for s in seqs)
    C0 = (tuple(0 for _ in seqs) if starts is None
          else tuple(int(c) for c in starts))
    if len(C0) != len(seqs) or any(not 0 <= c <= n
                                   for c, n in zip(C0, NP)):
        raise ValueError(f"starts {C0} out of range for page counts {NP}")
    p = ptg.PTGBuilder(name, KV=kv, T=T, SEQS=tuple(seqs), NP=NP,
                       C0=C0, NS=len(seqs))
    t = p.task("PF",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               c=lambda g, l: range(g.C0[l.s], g.NP[l.s]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.c))
    ft = t.flow("T", ptg.READ)
    ft.input(data=("T", lambda g, l: (g.SEQS[l.s], l.c)))
    fkv = t.flow("KV", ptg.RW)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.c)))
    fkv.output(data=("KV", lambda g, l: (g.SEQS[l.s], l.c)))

    def body(es: Any, task: Any, g: Any, l: Any) -> None:
        chunk = np.asarray(task.flow_data("T").value)
        kvw = task.flow_data("KV")
        kvw.value = np.array(chunk, copy=True)
        kvw.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="llm_prefill_copy")
    # the dyld names the traceable twin (ops/ragged_attention.py), so
    # the pool lowers/warms (llm_prefill_tail) and the device tier can
    # vmap-batch PF tasks; the CPU body stays the plain copy
    t.body(body, dyld="llm_prefill_copy")
    return p.build()


def decode_step_ptg(kv: PagedKVCollection, Q: DictCollection,
                    O: DictCollection, seqs: Sequence[Any],
                    devices: str = "cpu",
                    name: str = "llm_decode") -> ptg.PTGTaskpool:
    """One decode iteration for every listed sequence.

    Callers must have made the write slot real first
    (:meth:`PagedKVCollection.ensure_tail_slot`), so ``NP[s] >= 1`` and
    the tail page is private — the builder snapshots the page counts.
    """
    NP = tuple(kv.npages(s) for s in seqs)
    assert all(n >= 1 for n in NP), \
        "decode needs ensure_tail_slot() first (NP >= 1)"
    H, D = kv.num_heads, kv.head_dim
    p = ptg.PTGBuilder(name, KV=kv, Q=Q, O=O, SEQS=tuple(seqs), NP=NP,
                       NS=len(seqs))

    t = p.task("ATTN",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               p=lambda g, l: range(g.NP[l.s]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.p))
    # drain long page chains first: the step's critical path
    t.priority(lambda g, l: g.NP[l.s] - l.p)
    fq = t.flow("Q", ptg.READ)
    fq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)))
    fkv = t.flow("KV", ptg.READ)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.p)))
    facc = t.flow("ACC", ptg.RW, dtt=TileType((H, D + 2), np.float32))
    facc.input(new=True, guard=lambda g, l: l.p == 0)
    facc.input(pred=("ATTN", "ACC", lambda g, l: {"s": l.s, "p": l.p - 1}),
               guard=lambda g, l: l.p > 0)
    facc.output(succ=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "p": l.p + 1}),
                guard=lambda g, l: l.p < g.NP[l.s] - 1)
    facc.output(succ=("OUT", "ACC", lambda g, l: {"s": l.s}),
                guard=lambda g, l: l.p == g.NP[l.s] - 1)

    def attn_body(es: Any, task: Any, g: Any, l: Any) -> None:
        acc = task.flow_data("ACC")
        acc.value = ra.attn_page_update_np(
            np.asarray(task.flow_data("Q").value),
            np.asarray(task.flow_data("KV").value),
            np.asarray(acc.value))
        acc.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="ragged_attn_page")
    t.body(attn_body)

    o = p.task("OUT", s=ptg.span(0, lambda g, l: g.NS - 1))
    o.affinity("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1))
    foacc = o.flow("ACC", ptg.READ)
    foacc.input(pred=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "p": g.NP[l.s] - 1}))
    foq = o.flow("Q", ptg.READ)
    foq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)))
    fkvw = o.flow("KVW", ptg.RW)
    fkvw.input(data=("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1)))
    fkvw.output(data=("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1)))
    fo = o.flow("O", ptg.WRITE, dtt=TileType((H, D), np.float32))
    fo.input(new=True)
    fo.output(data=("O", lambda g, l: (g.SEQS[l.s],)))

    def out_body(es: Any, task: Any, g: Any, l: Any) -> None:
        kvw = task.flow_data("KVW")
        oc = task.flow_data("O")
        new_page, out = ra.attn_out_np(
            np.asarray(task.flow_data("ACC").value),
            np.asarray(task.flow_data("Q").value),
            np.asarray(kvw.value))
        kvw.value = new_page
        kvw.version += 1
        oc.value = out
        oc.version += 1

    if devices in ("auto", "tpu"):
        o.body(device="tpu", dyld="ragged_attn_out")
    o.body(out_body)
    return p.build()


def preallocate_decode_steps(kv: PagedKVCollection, seq: Any,
                             k: int) -> None:
    """Make ``k`` autoregressive write slots real BEFORE the superpool is
    built: token positions are deterministic (``seq_len .. seq_len+k-1``),
    so every tail page the k steps will touch can be allocated — and a
    fork-shared tail copy-on-write privatized — at build time.  (The
    builder re-derives the per-step page schedule itself from the
    ledger; this only has to make the pages exist.)"""
    if k < 1:
        raise ValueError("k must be >= 1")
    P = kv.page_size
    L0 = kv.seq_len(seq)
    kv.ensure_tail_slot(seq)            # CoW-privatize + first write page
    last_page = (L0 + k - 1) // P
    while kv.npages(seq) <= last_page:
        kv.alloc_page(seq)              # fresh pages are private + zeroed


def decode_superpool_ptg(kv: PagedKVCollection, Q: DictCollection,
                         O: DictCollection, TOK: DictCollection,
                         EMB: DictCollection, seqs: Sequence[Any],
                         steps: Sequence[int], devices: str = "cpu",
                         name: str = "llm_superpool") -> ptg.PTGTaskpool:
    """ONE PTG pool spanning ``steps[i]`` autoregressive decode
    iterations for each listed sequence — the k-step superpool (ISSUE 9).

    Per step t of sequence s::

        ATTN(s,t,p)  online-softmax of q(s,t) over page p, ACC threading
        OUT(s,t)     finalize -> SAMPLE; append q-token k/v to the tail
        SAMPLE(s,t)  in-graph greedy argmax over OUT's logits: writes
                     TOK(s,t) (the token the host reads) and feeds the
                     NEXT step's query q3(token) to ATTN/OUT(s,t+1)

    The host loop runs once per k tokens instead of once per token: the
    per-pool submit/termdet overhead (~1-2 ms) amortizes 1/k, and the
    whole k-step DAG is one graphcheck-verified region-lowerable graph.

    Callers must have (a) preallocated every step's write slot
    (:func:`preallocate_decode_steps` — positions are deterministic),
    (b) seeded ``Q(seq)`` with the current token's q3 stack and
    ``TOK(seq, -1)`` with ``[token, 0, eos]`` (``eos < 0`` = disabled),
    and (c) loaded ``EMB(0,)`` with the model's precomputed q3 stack
    table (:meth:`~parsec_tpu.llm.model.ToyLM.q3_table`).  EOS
    and early-finishing streams are handled by predicated step bodies
    (:func:`~parsec_tpu.ops.ragged_attention.sample_step_np`): a
    finished stream's remaining tasks run but change nothing, so a
    mid-superpool finish wastes at most its own tail tasks.
    """
    P = kv.page_size
    NS = len(seqs)
    S = tuple(int(k) for k in steps)
    if len(S) != NS or any(k < 1 for k in S):
        raise ValueError("steps must give every sequence >= 1 step")
    L0 = tuple(kv.seq_len(s) for s in seqs)
    # deterministic per-(seq, step) schedule: NP[t] pages attended, WP[t]
    # the append page, LW[t][p] the last step < t writing page p (-1:
    # frozen — read straight from the collection), RD[t] the later steps
    # whose ATTN re-reads the page OUT(t) wrote
    NP, WP, LW, RD = [], [], [], []
    for si, s in enumerate(seqs):
        wp_s = tuple((L0[si] + t) // P for t in range(S[si]))
        np_s = tuple(w + 1 for w in wp_s)
        if kv.npages(s) < np_s[-1]:
            raise ValueError(
                f"superpool needs preallocate_decode_steps() first: "
                f"seq {s!r} has {kv.npages(s)} pages, its {S[si]}-step "
                f"schedule needs {np_s[-1]}")
        lw_s = []
        for t in range(S[si]):
            lw_s.append(tuple(
                max((tp_ for tp_ in range(t) if wp_s[tp_] == p),
                    default=-1)
                for p in range(np_s[t])))
        rd_s = tuple(tuple(tt for tt in range(t + 1, S[si])
                           if lw_s[tt][wp_s[t]] == t)
                     for t in range(S[si]))
        NP.append(np_s)
        WP.append(wp_s)
        LW.append(tuple(lw_s))
        RD.append(rd_s)
    H, D = kv.num_heads, kv.head_dim
    p = ptg.PTGBuilder(name, KV=kv, Q=Q, O=O, TOK=TOK, EMB=EMB,
                       SEQS=tuple(seqs), NS=NS, S=S, NP=tuple(NP),
                       WP=tuple(WP), LW=tuple(LW), RD=tuple(RD))

    t = p.task("ATTN",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               t=lambda g, l: range(g.S[l.s]),
               p=lambda g, l: range(g.NP[l.s][l.t]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.p))
    # drain earlier steps and long page chains first: the critical path
    t.priority(lambda g, l: (g.S[l.s] - l.t) * 1024
               + g.NP[l.s][l.t] - l.p)
    fq = t.flow("Q", ptg.READ)
    fq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)),
             guard=lambda g, l: l.t == 0)
    fq.input(pred=("SAMPLE", "QN",
                   lambda g, l: {"s": l.s, "t": l.t - 1}),
             guard=lambda g, l: l.t > 0)
    fkv = t.flow("KV", ptg.READ)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.p)),
              guard=lambda g, l: g.LW[l.s][l.t][l.p] < 0)
    fkv.input(pred=("OUT", "KVW",
                    lambda g, l: {"s": l.s, "t": g.LW[l.s][l.t][l.p]}),
              guard=lambda g, l: g.LW[l.s][l.t][l.p] >= 0)
    facc = t.flow("ACC", ptg.RW, dtt=TileType((H, D + 2), np.float32))
    facc.input(new=True, guard=lambda g, l: l.p == 0)
    facc.input(pred=("ATTN", "ACC",
                     lambda g, l: {"s": l.s, "t": l.t, "p": l.p - 1}),
               guard=lambda g, l: l.p > 0)
    facc.output(succ=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "t": l.t, "p": l.p + 1}),
                guard=lambda g, l: l.p < g.NP[l.s][l.t] - 1)
    facc.output(succ=("OUT", "ACC", lambda g, l: {"s": l.s, "t": l.t}),
                guard=lambda g, l: l.p == g.NP[l.s][l.t] - 1)

    def attn_body(es: Any, task: Any, g: Any, l: Any) -> None:
        acc = task.flow_data("ACC")
        acc.value = ra.attn_page_update_np(
            np.asarray(task.flow_data("Q").value),
            np.asarray(task.flow_data("KV").value),
            np.asarray(acc.value))
        acc.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="ragged_attn_page")
    t.body(attn_body, dyld="ragged_attn_page")

    o = p.task("OUT", s=ptg.span(0, lambda g, l: g.NS - 1),
               t=lambda g, l: range(g.S[l.s]))
    o.affinity("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t]))
    o.priority(lambda g, l: (g.S[l.s] - l.t) * 1024)
    foacc = o.flow("ACC", ptg.READ)
    foacc.input(pred=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "t": l.t,
                                    "p": g.NP[l.s][l.t] - 1}))
    foq = o.flow("Q", ptg.READ)
    foq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)),
              guard=lambda g, l: l.t == 0)
    foq.input(pred=("SAMPLE", "QN",
                    lambda g, l: {"s": l.s, "t": l.t - 1}),
              guard=lambda g, l: l.t > 0)
    fkvw = o.flow("KVW", ptg.RW)
    fkvw.input(data=("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t])),
               guard=lambda g, l: l.t == 0
               or g.WP[l.s][l.t] != g.WP[l.s][l.t - 1])
    fkvw.input(pred=("OUT", "KVW",
                     lambda g, l: {"s": l.s, "t": l.t - 1}),
               guard=lambda g, l: l.t > 0
               and g.WP[l.s][l.t] == g.WP[l.s][l.t - 1])
    fkvw.output(data=("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t])))
    fkvw.output(succ=("OUT", "KVW",
                      lambda g, l: {"s": l.s, "t": l.t + 1}),
                guard=lambda g, l: l.t + 1 < g.S[l.s]
                and g.WP[l.s][l.t + 1] == g.WP[l.s][l.t])
    fkvw.output(succ=("ATTN", "KV",
                      lambda g, l: [{"s": l.s, "t": tt,
                                     "p": g.WP[l.s][l.t]}
                                    for tt in g.RD[l.s][l.t]]),
                guard=lambda g, l: bool(g.RD[l.s][l.t]))
    fo = o.flow("O", ptg.WRITE, dtt=TileType((H, D), np.float32))
    fo.input(new=True)
    fo.output(succ=("SAMPLE", "O", lambda g, l: {"s": l.s, "t": l.t}))
    fo.output(data=("O", lambda g, l: (g.SEQS[l.s],)),
              guard=lambda g, l: l.t == g.S[l.s] - 1)

    def out_body(es: Any, task: Any, g: Any, l: Any) -> None:
        kvw = task.flow_data("KVW")
        oc = task.flow_data("O")
        new_page, out = ra.attn_out_np(
            np.asarray(task.flow_data("ACC").value),
            np.asarray(task.flow_data("Q").value),
            np.asarray(kvw.value))
        kvw.value = new_page
        kvw.version += 1
        oc.value = out
        oc.version += 1

    if devices in ("auto", "tpu"):
        o.body(device="tpu", dyld="ragged_attn_out")
    o.body(out_body, dyld="ragged_attn_out")

    sm = p.task("SAMPLE", s=ptg.span(0, lambda g, l: g.NS - 1),
                t=lambda g, l: range(g.S[l.s]))
    sm.affinity("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t]))
    sm.priority(lambda g, l: (g.S[l.s] - l.t) * 1024)
    fso = sm.flow("O", ptg.READ)
    fso.input(pred=("OUT", "O", lambda g, l: {"s": l.s, "t": l.t}))
    fst = sm.flow("TOK", ptg.RW, dtt=TileType((3,), np.float32))
    fst.input(data=("TOK", lambda g, l: (g.SEQS[l.s], -1)),
              guard=lambda g, l: l.t == 0)
    fst.input(pred=("SAMPLE", "TOK",
                    lambda g, l: {"s": l.s, "t": l.t - 1}),
              guard=lambda g, l: l.t > 0)
    fst.output(data=("TOK", lambda g, l: (g.SEQS[l.s], l.t)))
    fst.output(succ=("SAMPLE", "TOK",
                     lambda g, l: {"s": l.s, "t": l.t + 1}),
               guard=lambda g, l: l.t < g.S[l.s] - 1)
    fse = sm.flow("EMB", ptg.READ)
    fse.input(data=("EMB", lambda g, l: (0,)))
    fsq = sm.flow("QN", ptg.WRITE, dtt=TileType((3, H, D), np.float32))
    fsq.input(new=True)
    fsq.output(succ=("ATTN", "Q",
                     lambda g, l: [{"s": l.s, "t": l.t + 1, "p": pp}
                                   for pp in range(g.NP[l.s][l.t + 1])]),
               guard=lambda g, l: l.t < g.S[l.s] - 1)
    fsq.output(succ=("OUT", "Q",
                     lambda g, l: {"s": l.s, "t": l.t + 1}),
               guard=lambda g, l: l.t < g.S[l.s] - 1)

    def sample_body(es: Any, task: Any, g: Any, l: Any) -> None:
        tok = task.flow_data("TOK")
        qn = task.flow_data("QN")
        tok_new, qn_new = ra.sample_step_np(
            np.asarray(task.flow_data("O").value),
            np.asarray(tok.value),
            np.asarray(task.flow_data("EMB").value))
        tok.value = tok_new
        tok.version += 1
        qn.value = qn_new
        qn.version += 1

    if devices in ("auto", "tpu"):
        sm.body(device="tpu", dyld="llm_sample")
    sm.body(sample_body, dyld="llm_sample")
    return p.build()


def prefill_chunks(model: Any, kv: PagedKVCollection, seq: Any,
                   tokens: Sequence[int]) -> dict[tuple, np.ndarray]:
    """Host-side prefill prep: allocate ``seq``'s pages for ``tokens``
    and return the ``(seq, chunk) -> tile`` map the T collection serves.
    Advances the length ledger — the PF tasks only move the bytes.

    Chunk indices continue from the sequence's CURRENT page count, so a
    prefix-cache adoptee (first ``m`` pages CoW-shared from the trie,
    ledger at the page boundary) prefills only its unmatched tail:
    ``tokens`` are then ``prompt[m * page_size:-1]`` and land in pages
    ``m, m+1, ...`` — a fresh sequence starts at chunk 0 unchanged."""
    P = kv.page_size
    chunks: dict[tuple, np.ndarray] = {}
    n = len(tokens)
    c0 = kv.npages(seq)
    for j in range((n + P - 1) // P):
        kv.alloc_page(seq)
        part = tokens[j * P:(j + 1) * P]
        tile = np.zeros(kv.default_dtt.shape, kv.dtype)
        for i, tok in enumerate(part):
            q3 = model.q3(tok)
            tile[0, i] = q3[1]
            tile[1, i] = q3[2]
        tile[META_CH, 0, 0, 0] = len(part)
        chunks[(seq, c0 + j)] = tile
    kv.note_appended(seq, n)
    return chunks


def seed_emb_table(model: Any, EMB: DictCollection) -> None:
    """Load ``EMB(0,)`` with the model's precomputed ``(V, 3, H, D)``
    q3 stack table — the tile the in-graph SAMPLE class computes logits
    and next-step queries from (one gather per token)."""
    ec = EMB.data_of(0).get_copy(0)
    ec.value = np.array(model.q3_table(), copy=True)
    ec.version += 1


def seed_stream_step(model: Any, Q: DictCollection, TOK: DictCollection,
                     seq: Any, token: int, *,
                     eos: int | None = None) -> None:
    """Seed ONE stream's per-iteration inputs: ``Q(seq)`` with the
    current token's q3 stack and ``TOK(seq, -1)`` with the
    ``[token, done=0, eos]`` chain-seed tile (``eos < 0`` = disabled) —
    the layout contract the SAMPLE bodies read.  The batcher calls this
    per stream per superpool; if the layout changes, it changes HERE
    and in the kernel, nowhere else."""
    qc = Q.data_of(seq).get_copy(0)
    qc.value = model.q3(token)
    qc.version += 1
    t0 = TOK.data_of(seq, -1).get_copy(0)
    t0.value = np.array([float(token), 0.0,
                         -1.0 if eos is None else float(eos)],
                        np.float32)
    t0.version += 1


def seed_decode_superpool(model: Any, kv: PagedKVCollection,
                          Q: DictCollection, TOK: DictCollection,
                          EMB: DictCollection,
                          prompts: dict[Any, Sequence[int]],
                          steps: dict[Any, int], *,
                          eos: int | None = None) -> None:
    """Host-side prep that makes :func:`decode_superpool_ptg`'s input
    contract executable: prefill each prompt's pages in place (no
    runtime), preallocate every step's write slot, and seed the
    collections through the same :func:`seed_emb_table` /
    :func:`seed_stream_step` the batcher uses.  Pool-level tests build
    on this instead of re-deriving the seeding contract."""
    seed_emb_table(model, EMB)
    for seq, prompt in prompts.items():
        kv.alloc_seq(seq)
        for key, tile in prefill_chunks(model, kv, seq,
                                        prompt[:-1]).items():
            pg = kv.data_of(*key).get_copy(0)
            pg.value = np.array(tile, copy=True)
            pg.version += 1
        preallocate_decode_steps(kv, seq, steps[seq])
        seed_stream_step(model, Q, TOK, seq, prompt[-1], eos=eos)


def read_token_chain(TOK: DictCollection, seq: Any,
                     k: int) -> tuple[list[int], bool]:
    """Read a sequence's k-step TOK chain the way the batcher does:
    tokens past the step whose done flag fired are the predicated tail
    and are never surfaced.  Returns ``(tokens, done)`` — ``done`` is
    the last surfaced step's flag, so an EOS on the final step still
    reads as finished."""
    toks: list[int] = []
    done = False
    for t in range(k):
        v = np.asarray(TOK.data_of(seq, t).newest_copy().value)
        if not done:
            toks.append(int(round(float(v[0]))))
            done = bool(v[1] > 0.5)
    return toks, done
