"""Prefill and ragged-decode task classes over the paged KV cache.

The LLM workload expressed in the runtime's own terms (ROADMAP: "ragged
attention task class per Ragged Paged Attention", arxiv 2604.15464):
plain PTG taskpools, so graphcheck statically verifies the per-step
dataflow (edge symmetry, WAR ordering against the KV pages, page-bounds
via :meth:`PagedKVCollection.has_key`) before a single token moves.

**PF(s, c)** — prefill: copy prompt chunk ``c`` of sequence ``s`` into
its KV page.  Embarrassingly parallel across chunks and sequences.

**ATTN(s, p)** — one query against one KV page, online-softmax state
threading along the sequence's ragged page list::

    ATTN(s,0) -> ATTN(s,1) -> ... -> ATTN(s, NP[s]-1) -> OUT(s)

Page tiles are uniform ``(3, page_size, H, D)`` (the fill count rides
in the tensor — ``data_dist/paged_kv.py``), so every live sequence's
ATTN tasks are the SAME class with the SAME shapes: the TPU device
module's fused same-class dispatch (``device/tpu.py:_run_vmapped``)
batches them into one vmapped XLA call — continuous batching meets the
PR-2 batched dispatch at the kernel level.

**OUT(s)** — finalize the attention output into the O collection and
append the query token's k/v into the tail page.  The tail-page write
is ordered AFTER ``ATTN(s, NP-1)``'s read of the same page by the ACC
chain — the WAR edge graphcheck checks.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from .. import ptg
from ..data.datatype import TileType
from ..data_dist.collection import DictCollection
from ..data_dist.paged_kv import META_CH, PagedKVCollection
from ..ops import ragged_attention as ra


def prefill_ptg(kv: PagedKVCollection, T: DictCollection,
                seqs: Sequence[Any], devices: str = "cpu",
                name: str = "llm_prefill",
                starts: Sequence[int] | None = None) -> ptg.PTGTaskpool:
    """PF(s, c) over every allocated page of every listed sequence.
    ``T`` holds the prompt chunk tiles, keyed ``(seq, chunk)``, in the
    same ``(3, page_size, H, D)`` layout as the pages.

    ``starts[i]`` is sequence ``i``'s first chunk to fill — the
    **tail-only prefill** shape (ISSUE 11): a stream admitted through
    the prefix cache already shares its first ``starts[i]`` pages
    copy-on-write with the trie, and the PF tasks must neither redo nor
    overwrite them.  Default 0 everywhere = the full prefill."""
    NP = tuple(kv.npages(s) for s in seqs)
    C0 = (tuple(0 for _ in seqs) if starts is None
          else tuple(int(c) for c in starts))
    if len(C0) != len(seqs) or any(not 0 <= c <= n
                                   for c, n in zip(C0, NP)):
        raise ValueError(f"starts {C0} out of range for page counts {NP}")
    p = ptg.PTGBuilder(name, KV=kv, T=T, SEQS=tuple(seqs), NP=NP,
                       C0=C0, NS=len(seqs))
    t = p.task("PF",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               c=lambda g, l: range(g.C0[l.s], g.NP[l.s]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.c))
    ft = t.flow("T", ptg.READ)
    ft.input(data=("T", lambda g, l: (g.SEQS[l.s], l.c)))
    fkv = t.flow("KV", ptg.RW)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.c)))
    fkv.output(data=("KV", lambda g, l: (g.SEQS[l.s], l.c)))

    def body(es: Any, task: Any, g: Any, l: Any) -> None:
        chunk = np.asarray(task.flow_data("T").value)
        kvw = task.flow_data("KV")
        kvw.value = np.array(chunk, copy=True)
        kvw.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="llm_prefill_copy")
    # the dyld names the traceable twin (ops/ragged_attention.py), so
    # the pool lowers/warms (llm_prefill_tail) and the device tier can
    # vmap-batch PF tasks; the CPU body stays the plain copy
    t.body(body, dyld="llm_prefill_copy")
    return p.build()


def decode_step_ptg(kv: PagedKVCollection, Q: DictCollection,
                    O: DictCollection, seqs: Sequence[Any],
                    devices: str = "cpu",
                    name: str = "llm_decode") -> ptg.PTGTaskpool:
    """One decode iteration for every listed sequence.

    Callers must have made the write slot real first
    (:meth:`PagedKVCollection.ensure_tail_slot`), so ``NP[s] >= 1`` and
    the tail page is private — the builder snapshots the page counts.
    """
    NP = tuple(kv.npages(s) for s in seqs)
    assert all(n >= 1 for n in NP), \
        "decode needs ensure_tail_slot() first (NP >= 1)"
    H, D = kv.num_heads, kv.head_dim
    p = ptg.PTGBuilder(name, KV=kv, Q=Q, O=O, SEQS=tuple(seqs), NP=NP,
                       NS=len(seqs))

    t = p.task("ATTN",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               p=lambda g, l: range(g.NP[l.s]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.p))
    # drain long page chains first: the step's critical path
    t.priority(lambda g, l: g.NP[l.s] - l.p)
    fq = t.flow("Q", ptg.READ)
    fq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)))
    fkv = t.flow("KV", ptg.READ)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.p)))
    facc = t.flow("ACC", ptg.RW, dtt=TileType((H, D + 2), np.float32))
    facc.input(new=True, guard=lambda g, l: l.p == 0)
    facc.input(pred=("ATTN", "ACC", lambda g, l: {"s": l.s, "p": l.p - 1}),
               guard=lambda g, l: l.p > 0)
    facc.output(succ=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "p": l.p + 1}),
                guard=lambda g, l: l.p < g.NP[l.s] - 1)
    facc.output(succ=("OUT", "ACC", lambda g, l: {"s": l.s}),
                guard=lambda g, l: l.p == g.NP[l.s] - 1)

    def attn_body(es: Any, task: Any, g: Any, l: Any) -> None:
        acc = task.flow_data("ACC")
        acc.value = ra.attn_page_update_np(
            np.asarray(task.flow_data("Q").value),
            np.asarray(task.flow_data("KV").value),
            np.asarray(acc.value))
        acc.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="ragged_attn_page")
    t.body(attn_body)

    o = p.task("OUT", s=ptg.span(0, lambda g, l: g.NS - 1))
    o.affinity("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1))
    foacc = o.flow("ACC", ptg.READ)
    foacc.input(pred=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "p": g.NP[l.s] - 1}))
    foq = o.flow("Q", ptg.READ)
    foq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)))
    fkvw = o.flow("KVW", ptg.RW)
    fkvw.input(data=("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1)))
    fkvw.output(data=("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1)))
    fo = o.flow("O", ptg.WRITE, dtt=TileType((H, D), np.float32))
    fo.input(new=True)
    fo.output(data=("O", lambda g, l: (g.SEQS[l.s],)))

    def out_body(es: Any, task: Any, g: Any, l: Any) -> None:
        kvw = task.flow_data("KVW")
        oc = task.flow_data("O")
        new_page, out = ra.attn_out_np(
            np.asarray(task.flow_data("ACC").value),
            np.asarray(task.flow_data("Q").value),
            np.asarray(kvw.value))
        kvw.value = new_page
        kvw.version += 1
        oc.value = out
        oc.version += 1

    if devices in ("auto", "tpu"):
        o.body(device="tpu", dyld="ragged_attn_out")
    o.body(out_body)
    return p.build()


def preallocate_decode_steps(kv: PagedKVCollection, seq: Any,
                             k: int) -> None:
    """Make ``k`` autoregressive write slots real BEFORE the superpool is
    built: token positions are deterministic (``seq_len .. seq_len+k-1``),
    so every tail page the k steps will touch can be allocated — and a
    fork-shared tail copy-on-write privatized — at build time.  (The
    builder re-derives the per-step page schedule itself from the
    ledger; this only has to make the pages exist.)"""
    if k < 1:
        raise ValueError("k must be >= 1")
    P = kv.page_size
    L0 = kv.seq_len(seq)
    kv.ensure_tail_slot(seq)            # CoW-privatize + first write page
    last_page = (L0 + k - 1) // P
    while kv.npages(seq) <= last_page:
        kv.alloc_page(seq)              # fresh pages are private + zeroed


def _superpool_schedule(kv: PagedKVCollection, seqs: Sequence[Any],
                        steps: Sequence[int], kind: str):
    """The deterministic per-(seq, step/position) page schedule BOTH
    superpool builders share (the k-step SAMPLE pool and the
    speculative per-position pool append the same token positions):
    ``NP[t]`` pages attended, ``WP[t]`` the append page, ``LW[t][p]``
    the last step < t writing page p (-1: frozen — read straight from
    the collection), ``RD[t]`` the later steps whose ATTN re-reads the
    page step t wrote.  LW/RD are exactly the last-writer/reader
    tables graphcheck proves the cross-step (and speculative-tail)
    WAR/WAW ordering from — one derivation, two incarnations."""
    P = kv.page_size
    L0 = tuple(kv.seq_len(s) for s in seqs)
    NP, WP, LW, RD = [], [], [], []
    for si, s in enumerate(seqs):
        wp_s = tuple((L0[si] + t) // P for t in range(steps[si]))
        np_s = tuple(w + 1 for w in wp_s)
        if kv.npages(s) < np_s[-1]:
            raise ValueError(
                f"{kind} needs preallocate_decode_steps() first: "
                f"seq {s!r} has {kv.npages(s)} pages, its "
                f"{steps[si]}-step schedule needs {np_s[-1]}")
        lw_s = []
        for t in range(steps[si]):
            lw_s.append(tuple(
                max((tp_ for tp_ in range(t) if wp_s[tp_] == p),
                    default=-1)
                for p in range(np_s[t])))
        rd_s = tuple(tuple(tt for tt in range(t + 1, steps[si])
                           if lw_s[tt][wp_s[t]] == t)
                     for t in range(steps[si]))
        NP.append(np_s)
        WP.append(wp_s)
        LW.append(tuple(lw_s))
        RD.append(rd_s)
    return L0, tuple(NP), tuple(WP), tuple(LW), tuple(RD)


def decode_superpool_ptg(kv: PagedKVCollection, Q: DictCollection,
                         O: DictCollection, TOK: DictCollection,
                         EMB: DictCollection, seqs: Sequence[Any],
                         steps: Sequence[int], devices: str = "cpu",
                         name: str = "llm_superpool") -> ptg.PTGTaskpool:
    """ONE PTG pool spanning ``steps[i]`` autoregressive decode
    iterations for each listed sequence — the k-step superpool (ISSUE 9).

    Per step t of sequence s::

        ATTN(s,t,p)  online-softmax of q(s,t) over page p, ACC threading
        OUT(s,t)     finalize -> SAMPLE; append q-token k/v to the tail
        SAMPLE(s,t)  in-graph greedy argmax over OUT's logits: writes
                     TOK(s,t) (the token the host reads) and feeds the
                     NEXT step's query q3(token) to ATTN/OUT(s,t+1)

    The host loop runs once per k tokens instead of once per token: the
    per-pool submit/termdet overhead (~1-2 ms) amortizes 1/k, and the
    whole k-step DAG is one graphcheck-verified region-lowerable graph.

    Callers must have (a) preallocated every step's write slot
    (:func:`preallocate_decode_steps` — positions are deterministic),
    (b) seeded ``Q(seq)`` with the current token's q3 stack and
    ``TOK(seq, -1)`` with ``[token, 0, eos]`` (``eos < 0`` = disabled),
    and (c) loaded ``EMB(0,)`` with the model's precomputed q3 stack
    table (:meth:`~parsec_tpu.llm.model.ToyLM.q3_table`).  EOS
    and early-finishing streams are handled by predicated step bodies
    (:func:`~parsec_tpu.ops.ragged_attention.sample_step_np`): a
    finished stream's remaining tasks run but change nothing, so a
    mid-superpool finish wastes at most its own tail tasks.
    """
    NS = len(seqs)
    S = tuple(int(k) for k in steps)
    if len(S) != NS or any(k < 1 for k in S):
        raise ValueError("steps must give every sequence >= 1 step")
    _, NP, WP, LW, RD = _superpool_schedule(kv, seqs, S, "superpool")
    H, D = kv.num_heads, kv.head_dim
    p = ptg.PTGBuilder(name, KV=kv, Q=Q, O=O, TOK=TOK, EMB=EMB,
                       SEQS=tuple(seqs), NS=NS, S=S, NP=NP,
                       WP=WP, LW=LW, RD=RD)

    t = p.task("ATTN",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               t=lambda g, l: range(g.S[l.s]),
               p=lambda g, l: range(g.NP[l.s][l.t]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.p))
    # drain earlier steps and long page chains first: the critical path
    t.priority(lambda g, l: (g.S[l.s] - l.t) * 1024
               + g.NP[l.s][l.t] - l.p)
    fq = t.flow("Q", ptg.READ)
    fq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)),
             guard=lambda g, l: l.t == 0)
    fq.input(pred=("SAMPLE", "QN",
                   lambda g, l: {"s": l.s, "t": l.t - 1}),
             guard=lambda g, l: l.t > 0)
    fkv = t.flow("KV", ptg.READ)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.p)),
              guard=lambda g, l: g.LW[l.s][l.t][l.p] < 0)
    fkv.input(pred=("OUT", "KVW",
                    lambda g, l: {"s": l.s, "t": g.LW[l.s][l.t][l.p]}),
              guard=lambda g, l: g.LW[l.s][l.t][l.p] >= 0)
    facc = t.flow("ACC", ptg.RW, dtt=TileType((H, D + 2), np.float32))
    facc.input(new=True, guard=lambda g, l: l.p == 0)
    facc.input(pred=("ATTN", "ACC",
                     lambda g, l: {"s": l.s, "t": l.t, "p": l.p - 1}),
               guard=lambda g, l: l.p > 0)
    facc.output(succ=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "t": l.t, "p": l.p + 1}),
                guard=lambda g, l: l.p < g.NP[l.s][l.t] - 1)
    facc.output(succ=("OUT", "ACC", lambda g, l: {"s": l.s, "t": l.t}),
                guard=lambda g, l: l.p == g.NP[l.s][l.t] - 1)

    def attn_body(es: Any, task: Any, g: Any, l: Any) -> None:
        acc = task.flow_data("ACC")
        acc.value = ra.attn_page_update_np(
            np.asarray(task.flow_data("Q").value),
            np.asarray(task.flow_data("KV").value),
            np.asarray(acc.value))
        acc.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="ragged_attn_page")
    t.body(attn_body, dyld="ragged_attn_page")

    o = p.task("OUT", s=ptg.span(0, lambda g, l: g.NS - 1),
               t=lambda g, l: range(g.S[l.s]))
    o.affinity("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t]))
    o.priority(lambda g, l: (g.S[l.s] - l.t) * 1024)
    foacc = o.flow("ACC", ptg.READ)
    foacc.input(pred=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "t": l.t,
                                    "p": g.NP[l.s][l.t] - 1}))
    foq = o.flow("Q", ptg.READ)
    foq.input(data=("Q", lambda g, l: (g.SEQS[l.s],)),
              guard=lambda g, l: l.t == 0)
    foq.input(pred=("SAMPLE", "QN",
                    lambda g, l: {"s": l.s, "t": l.t - 1}),
              guard=lambda g, l: l.t > 0)
    fkvw = o.flow("KVW", ptg.RW)
    fkvw.input(data=("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t])),
               guard=lambda g, l: l.t == 0
               or g.WP[l.s][l.t] != g.WP[l.s][l.t - 1])
    fkvw.input(pred=("OUT", "KVW",
                     lambda g, l: {"s": l.s, "t": l.t - 1}),
               guard=lambda g, l: l.t > 0
               and g.WP[l.s][l.t] == g.WP[l.s][l.t - 1])
    fkvw.output(data=("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t])))
    fkvw.output(succ=("OUT", "KVW",
                      lambda g, l: {"s": l.s, "t": l.t + 1}),
                guard=lambda g, l: l.t + 1 < g.S[l.s]
                and g.WP[l.s][l.t + 1] == g.WP[l.s][l.t])
    fkvw.output(succ=("ATTN", "KV",
                      lambda g, l: [{"s": l.s, "t": tt,
                                     "p": g.WP[l.s][l.t]}
                                    for tt in g.RD[l.s][l.t]]),
                guard=lambda g, l: bool(g.RD[l.s][l.t]))
    fo = o.flow("O", ptg.WRITE, dtt=TileType((H, D), np.float32))
    fo.input(new=True)
    fo.output(succ=("SAMPLE", "O", lambda g, l: {"s": l.s, "t": l.t}))
    fo.output(data=("O", lambda g, l: (g.SEQS[l.s],)),
              guard=lambda g, l: l.t == g.S[l.s] - 1)

    def out_body(es: Any, task: Any, g: Any, l: Any) -> None:
        kvw = task.flow_data("KVW")
        oc = task.flow_data("O")
        new_page, out = ra.attn_out_np(
            np.asarray(task.flow_data("ACC").value),
            np.asarray(task.flow_data("Q").value),
            np.asarray(kvw.value))
        kvw.value = new_page
        kvw.version += 1
        oc.value = out
        oc.version += 1

    if devices in ("auto", "tpu"):
        o.body(device="tpu", dyld="ragged_attn_out")
    o.body(out_body, dyld="ragged_attn_out")

    sm = p.task("SAMPLE", s=ptg.span(0, lambda g, l: g.NS - 1),
                t=lambda g, l: range(g.S[l.s]))
    sm.affinity("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t]))
    sm.priority(lambda g, l: (g.S[l.s] - l.t) * 1024)
    fso = sm.flow("O", ptg.READ)
    fso.input(pred=("OUT", "O", lambda g, l: {"s": l.s, "t": l.t}))
    fst = sm.flow("TOK", ptg.RW, dtt=TileType((3,), np.float32))
    fst.input(data=("TOK", lambda g, l: (g.SEQS[l.s], -1)),
              guard=lambda g, l: l.t == 0)
    fst.input(pred=("SAMPLE", "TOK",
                    lambda g, l: {"s": l.s, "t": l.t - 1}),
              guard=lambda g, l: l.t > 0)
    fst.output(data=("TOK", lambda g, l: (g.SEQS[l.s], l.t)))
    fst.output(succ=("SAMPLE", "TOK",
                     lambda g, l: {"s": l.s, "t": l.t + 1}),
               guard=lambda g, l: l.t < g.S[l.s] - 1)
    fse = sm.flow("EMB", ptg.READ)
    fse.input(data=("EMB", lambda g, l: (0,)))
    fsq = sm.flow("QN", ptg.WRITE, dtt=TileType((3, H, D), np.float32))
    fsq.input(new=True)
    fsq.output(succ=("ATTN", "Q",
                     lambda g, l: [{"s": l.s, "t": l.t + 1, "p": pp}
                                   for pp in range(g.NP[l.s][l.t + 1])]),
               guard=lambda g, l: l.t < g.S[l.s] - 1)
    fsq.output(succ=("OUT", "Q",
                     lambda g, l: {"s": l.s, "t": l.t + 1}),
               guard=lambda g, l: l.t < g.S[l.s] - 1)

    def sample_body(es: Any, task: Any, g: Any, l: Any) -> None:
        tok = task.flow_data("TOK")
        qn = task.flow_data("QN")
        tok_new, qn_new = ra.sample_step_np(
            np.asarray(task.flow_data("O").value),
            np.asarray(tok.value),
            np.asarray(task.flow_data("EMB").value))
        tok.value = tok_new
        tok.version += 1
        qn.value = qn_new
        qn.version += 1

    if devices in ("auto", "tpu"):
        sm.body(device="tpu", dyld="llm_sample")
    sm.body(sample_body, dyld="llm_sample")
    return p.build()


def spec_superpool_ptg(kv: PagedKVCollection, DRAFT: DictCollection,
                       O: DictCollection, STOK: DictCollection,
                       DTOK: DictCollection, EMB: DictCollection,
                       seqs: Sequence[Any], positions: Sequence[int],
                       devices: str = "cpu",
                       name: str = "llm_spec") -> ptg.PTGTaskpool:
    """ONE PTG pool verifying ``positions[i]`` speculative draft
    positions for each listed sequence — the **speculative superpool**
    (ISSUE 12), the draft-k-verify generalization of
    :func:`decode_superpool_ptg`.

    Where the PR-9 superpool chains step t's query out of step t-1's
    SAMPLE (a serial in-graph dependence), here EVERY position's query
    is known at build time — position 0 is the stream's real current
    token and positions 1.. are the drafter's proposals — so the page
    schedule is identical but the Q edges are plain data reads::

        ATTN(s,t,p)   q3(draft_t) over page p, ACC threading — ALL
                      positions' frozen-page reads run in parallel (and
                      vmap-batch: one class, one shape); only the tail
                      page serializes through OUT's appends
        OUT(s,t)      finalize -> VERIFY; append draft_t's k/v to the
                      tail page (speculative — rolled back on reject)
        VERIFY(s,t)   the in-graph accept decision: emits the target's
                      token at live positions, kills the chain at the
                      first draft mismatch (ops/ragged_attention
                      .verify_step_np) — rejected-branch tail tasks run
                      but change nothing, the PR-9 EOS predication shape

    The host reads the STOK chain once per pool
    (:func:`read_spec_chain`): live positions' tokens surface — between
    1 (position 0 always) and ``positions[i]`` per stream — and the
    batcher rolls the rejected appends back with
    :meth:`PagedKVCollection.rollback_tail` before the next superpool,
    so a rejected draft can never leak stale KV.

    Callers must have preallocated every position's write slot
    (:func:`preallocate_decode_steps` — positions are deterministic)
    and seeded DRAFT/DTOK/STOK via :func:`seed_spec_stream` plus
    ``EMB(0,)`` via :func:`seed_emb_table`.  The WAR/WAW ordering of
    the speculative tail (position t's tail-page read AFTER position
    t-1's append, re-reads of an earlier position's written page) rides
    the same static last-writer/reader tables (LW/RD) graphcheck
    already proves for the PR-9 superpool — the speculative tail is
    schedule-identical, only the acceptance is late-bound.
    """
    NS = len(seqs)
    S = tuple(int(n) for n in positions)
    if len(S) != NS or any(n < 1 for n in S):
        raise ValueError("positions must give every sequence >= 1 "
                         "speculative position")
    # identical schedule math to decode_superpool_ptg (position t
    # appends token L0+t), shared via _superpool_schedule — and with it
    # the WAR/WAW edges graphcheck proves
    _, NP, WP, LW, RD = _superpool_schedule(kv, seqs, S,
                                            "spec superpool")
    H, D = kv.num_heads, kv.head_dim
    p = ptg.PTGBuilder(name, KV=kv, DRAFT=DRAFT, O=O, STOK=STOK,
                       DTOK=DTOK, EMB=EMB, SEQS=tuple(seqs), NS=NS, S=S,
                       NP=NP, WP=WP, LW=LW, RD=RD)

    t = p.task("ATTN",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               t=lambda g, l: range(g.S[l.s]),
               p=lambda g, l: range(g.NP[l.s][l.t]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.p))
    # the tail-page append chain is the only serial path: drain earlier
    # positions and long page chains first
    t.priority(lambda g, l: (g.S[l.s] - l.t) * 1024
               + g.NP[l.s][l.t] - l.p)
    fq = t.flow("Q", ptg.READ)
    # the structural difference vs the PR-9 superpool: the query is a
    # BUILD-TIME datum (the draft), not SAMPLE(t-1)'s output — every
    # position's frozen-page ATTN is immediately runnable
    fq.input(data=("DRAFT", lambda g, l: (g.SEQS[l.s], l.t)))
    fkv = t.flow("KV", ptg.READ)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.p)),
              guard=lambda g, l: g.LW[l.s][l.t][l.p] < 0)
    fkv.input(pred=("OUT", "KVW",
                    lambda g, l: {"s": l.s, "t": g.LW[l.s][l.t][l.p]}),
              guard=lambda g, l: g.LW[l.s][l.t][l.p] >= 0)
    facc = t.flow("ACC", ptg.RW, dtt=TileType((H, D + 2), np.float32))
    facc.input(new=True, guard=lambda g, l: l.p == 0)
    facc.input(pred=("ATTN", "ACC",
                     lambda g, l: {"s": l.s, "t": l.t, "p": l.p - 1}),
               guard=lambda g, l: l.p > 0)
    facc.output(succ=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "t": l.t, "p": l.p + 1}),
                guard=lambda g, l: l.p < g.NP[l.s][l.t] - 1)
    facc.output(succ=("OUT", "ACC", lambda g, l: {"s": l.s, "t": l.t}),
                guard=lambda g, l: l.p == g.NP[l.s][l.t] - 1)

    def attn_body(es: Any, task: Any, g: Any, l: Any) -> None:
        acc = task.flow_data("ACC")
        acc.value = ra.attn_page_update_np(
            np.asarray(task.flow_data("Q").value),
            np.asarray(task.flow_data("KV").value),
            np.asarray(acc.value))
        acc.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="ragged_attn_page")
    t.body(attn_body, dyld="ragged_attn_page")

    o = p.task("OUT", s=ptg.span(0, lambda g, l: g.NS - 1),
               t=lambda g, l: range(g.S[l.s]))
    o.affinity("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t]))
    o.priority(lambda g, l: (g.S[l.s] - l.t) * 1024)
    foacc = o.flow("ACC", ptg.READ)
    foacc.input(pred=("ATTN", "ACC",
                      lambda g, l: {"s": l.s, "t": l.t,
                                    "p": g.NP[l.s][l.t] - 1}))
    foq = o.flow("Q", ptg.READ)
    foq.input(data=("DRAFT", lambda g, l: (g.SEQS[l.s], l.t)))
    fkvw = o.flow("KVW", ptg.RW)
    fkvw.input(data=("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t])),
               guard=lambda g, l: l.t == 0
               or g.WP[l.s][l.t] != g.WP[l.s][l.t - 1])
    fkvw.input(pred=("OUT", "KVW",
                     lambda g, l: {"s": l.s, "t": l.t - 1}),
               guard=lambda g, l: l.t > 0
               and g.WP[l.s][l.t] == g.WP[l.s][l.t - 1])
    fkvw.output(data=("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t])))
    fkvw.output(succ=("OUT", "KVW",
                      lambda g, l: {"s": l.s, "t": l.t + 1}),
                guard=lambda g, l: l.t + 1 < g.S[l.s]
                and g.WP[l.s][l.t + 1] == g.WP[l.s][l.t])
    fkvw.output(succ=("ATTN", "KV",
                      lambda g, l: [{"s": l.s, "t": tt,
                                     "p": g.WP[l.s][l.t]}
                                    for tt in g.RD[l.s][l.t]]),
                guard=lambda g, l: bool(g.RD[l.s][l.t]))
    fo = o.flow("O", ptg.WRITE, dtt=TileType((H, D), np.float32))
    fo.input(new=True)
    fo.output(succ=("VERIFY", "O", lambda g, l: {"s": l.s, "t": l.t}))
    fo.output(data=("O", lambda g, l: (g.SEQS[l.s],)),
              guard=lambda g, l: l.t == g.S[l.s] - 1)

    def out_body(es: Any, task: Any, g: Any, l: Any) -> None:
        kvw = task.flow_data("KVW")
        oc = task.flow_data("O")
        new_page, out = ra.attn_out_np(
            np.asarray(task.flow_data("ACC").value),
            np.asarray(task.flow_data("Q").value),
            np.asarray(kvw.value))
        kvw.value = new_page
        kvw.version += 1
        oc.value = out
        oc.version += 1

    if devices in ("auto", "tpu"):
        o.body(device="tpu", dyld="ragged_attn_out")
    o.body(out_body, dyld="ragged_attn_out")

    vf = p.task("VERIFY", s=ptg.span(0, lambda g, l: g.NS - 1),
                t=lambda g, l: range(g.S[l.s]))
    vf.affinity("KV", lambda g, l: (g.SEQS[l.s], g.WP[l.s][l.t]))
    vf.priority(lambda g, l: (g.S[l.s] - l.t) * 1024)
    fvo = vf.flow("O", ptg.READ)
    fvo.input(pred=("OUT", "O", lambda g, l: {"s": l.s, "t": l.t}))
    fvs = vf.flow("STOK", ptg.RW, dtt=TileType((4,), np.float32))
    fvs.input(data=("STOK", lambda g, l: (g.SEQS[l.s], -1)),
              guard=lambda g, l: l.t == 0)
    fvs.input(pred=("VERIFY", "STOK",
                    lambda g, l: {"s": l.s, "t": l.t - 1}),
              guard=lambda g, l: l.t > 0)
    fvs.output(data=("STOK", lambda g, l: (g.SEQS[l.s], l.t)))
    fvs.output(succ=("VERIFY", "STOK",
                     lambda g, l: {"s": l.s, "t": l.t + 1}),
               guard=lambda g, l: l.t < g.S[l.s] - 1)
    fvd = vf.flow("DTOK", ptg.READ)
    fvd.input(data=("DTOK", lambda g, l: (g.SEQS[l.s], l.t)))
    fve = vf.flow("EMB", ptg.READ)
    fve.input(data=("EMB", lambda g, l: (0,)))

    def verify_body(es: Any, task: Any, g: Any, l: Any) -> None:
        st = task.flow_data("STOK")
        st.value = ra.verify_step_np(
            np.asarray(task.flow_data("O").value),
            np.asarray(st.value),
            np.asarray(task.flow_data("DTOK").value),
            np.asarray(task.flow_data("EMB").value))
        st.version += 1

    if devices in ("auto", "tpu"):
        vf.body(device="tpu", dyld="llm_verify")
    vf.body(verify_body, dyld="llm_verify")
    return p.build()


def _spec_attend_pages(L0: int, n: int, P: int) -> int:
    """Pages the batched spec pool's LAST position attends: position t
    sees tokens ``[0, L0+t)``, so the deepest read ends at token
    ``L0+n-2`` (the last position never attends its own append).  At
    least 1 — an empty cache still runs one (fully masked) page task."""
    return max(1, (L0 + n - 2) // P + 1)


def spec_batched_ptg(kv: PagedKVCollection, QS: DictCollection,
                     LIM: DictCollection, DTOKS: DictCollection,
                     VOUT: DictCollection, EMB: DictCollection,
                     seqs: Sequence[Any], positions: Sequence[int],
                     pad: int | None = None, devices: str = "cpu",
                     name: str = "llm_spec_batched") -> ptg.PTGTaskpool:
    """The BATCHED speculative superpool — the serving hot path's
    incarnation of draft-k-verify (ISSUE 12): the verify pass really is
    "one more batched ragged-attention call over the paged KV".

    Where :func:`spec_superpool_ptg` carries one task per (position,
    page) with in-graph appends (the predicated-branch incarnation the
    analysis sweep proves WAR/WAW-clean), here the host PRE-STAGES the
    whole draft chain's k/v into the tail slots at seed time
    (:func:`seed_spec_batched` — the slots are exactly the ones
    :meth:`~parsec_tpu.data_dist.paged_kv.PagedKVCollection
    .rollback_tail` scrubs on reject), and the pool collapses to::

        SATTN(s, p)   ALL positions' queries against page p in ONE body
                      (ops/ragged_attention.spec_attn_page_np), causal
                      per-position slot limits from the LIM tile; ACC
                      is the (S, H, D+2) flash-state stack, threaded
                      along the page chain
        SVERIFY(s)    finalize every position, sample the target's
                      tokens, compute the accepted prefix — ONE body
                      per stream, result in VOUT(seq)

    ``NP + 1`` tasks per stream per pool instead of ``~k * NP + 2k`` —
    per-task dispatch stops dominating the speculative win on the
    host-dispatched CPU path (the per-position pool gets the same
    collapse only from vmapped same-class device dispatch).  The pool
    only READS KV pages, so graphcheck is trivially clean; the
    write-side hazards live in the seed/rollback pair, which the
    batcher serializes against the pool (seed before submit, rollback
    after await — the same host-side discipline as seed_stream_step).

    ``pad``: pad every stream's position axis to this count (default:
    the pool's max) — uniform tile shapes are what let the device tier
    vmap SATTN across streams and keep the XLA cache warm across
    iterations.  Padded rows ride zero LIM limits and a zero query:
    they fold nothing in and VERIFY ignores them (the DTOKS count).
    """
    P = kv.page_size
    NS = len(seqs)
    S = tuple(int(n) for n in positions)
    if len(S) != NS or any(n < 1 for n in S):
        raise ValueError("positions must give every sequence >= 1 "
                         "speculative position")
    SP = max(S) if pad is None else int(pad)
    if SP < max(S):
        raise ValueError(f"pad {SP} below the pool's max positions "
                         f"{max(S)}")
    L0 = tuple(kv.seq_len(s) for s in seqs)
    NP = tuple(_spec_attend_pages(L0[i], S[i], P) for i in range(NS))
    for i, s in enumerate(seqs):
        need = (L0[i] + S[i] - 1) // P + 1
        if kv.npages(s) < need:
            raise ValueError(
                f"spec batched pool needs preallocate_decode_steps() "
                f"first: seq {s!r} has {kv.npages(s)} pages, its "
                f"{S[i]}-position schedule needs {need}")
    H, D = kv.num_heads, kv.head_dim
    p = ptg.PTGBuilder(name, KV=kv, QS=QS, LIM=LIM, DTOKS=DTOKS,
                       VOUT=VOUT, EMB=EMB, SEQS=tuple(seqs), NS=NS,
                       S=S, SP=SP, NP=NP)

    t = p.task("SATTN",
               s=ptg.span(0, lambda g, l: g.NS - 1),
               p=lambda g, l: range(g.NP[l.s]))
    t.affinity("KV", lambda g, l: (g.SEQS[l.s], l.p))
    # one serial ACC chain per stream: drain long chains first
    t.priority(lambda g, l: g.NP[l.s] - l.p)
    fq = t.flow("QS", ptg.READ)
    fq.input(data=("QS", lambda g, l: (g.SEQS[l.s],)))
    fkv = t.flow("KV", ptg.READ)
    fkv.input(data=("KV", lambda g, l: (g.SEQS[l.s], l.p)))
    fl = t.flow("LIM", ptg.READ)
    fl.input(data=("LIM", lambda g, l: (g.SEQS[l.s], l.p)))
    facc = t.flow("ACC", ptg.RW,
                  dtt=TileType((SP, H, D + 2), np.float32))
    facc.input(new=True, guard=lambda g, l: l.p == 0)
    facc.input(pred=("SATTN", "ACC",
                     lambda g, l: {"s": l.s, "p": l.p - 1}),
               guard=lambda g, l: l.p > 0)
    facc.output(succ=("SATTN", "ACC",
                      lambda g, l: {"s": l.s, "p": l.p + 1}),
                guard=lambda g, l: l.p < g.NP[l.s] - 1)
    facc.output(succ=("SVERIFY", "ACC", lambda g, l: {"s": l.s}),
                guard=lambda g, l: l.p == g.NP[l.s] - 1)

    def sattn_body(es: Any, task: Any, g: Any, l: Any) -> None:
        acc = task.flow_data("ACC")
        acc.value = ra.spec_attn_page_np(
            np.asarray(task.flow_data("QS").value),
            np.asarray(task.flow_data("KV").value),
            np.asarray(task.flow_data("LIM").value),
            np.asarray(acc.value))
        acc.version += 1

    if devices in ("auto", "tpu"):
        t.body(device="tpu", dyld="llm_spec_attn")
    t.body(sattn_body, dyld="llm_spec_attn")

    vf = p.task("SVERIFY", s=ptg.span(0, lambda g, l: g.NS - 1))
    vf.affinity("KV", lambda g, l: (g.SEQS[l.s], g.NP[l.s] - 1))
    fva = vf.flow("ACC", ptg.READ)
    fva.input(pred=("SATTN", "ACC",
                    lambda g, l: {"s": l.s, "p": g.NP[l.s] - 1}))
    fvd = vf.flow("DTOKS", ptg.READ)
    fvd.input(data=("DTOKS", lambda g, l: (g.SEQS[l.s],)))
    fve = vf.flow("EMB", ptg.READ)
    fve.input(data=("EMB", lambda g, l: (0,)))
    fvo = vf.flow("VOUT", ptg.WRITE,
                  dtt=TileType((SP + 2,), np.float32))
    fvo.input(new=True)
    fvo.output(data=("VOUT", lambda g, l: (g.SEQS[l.s],)))

    def sverify_body(es: Any, task: Any, g: Any, l: Any) -> None:
        vout = task.flow_data("VOUT")
        vout.value = ra.spec_verify_np(
            np.asarray(task.flow_data("ACC").value),
            np.asarray(task.flow_data("DTOKS").value),
            np.asarray(task.flow_data("EMB").value))
        vout.version += 1

    if devices in ("auto", "tpu"):
        vf.body(device="tpu", dyld="llm_spec_verify")
    vf.body(sverify_body, dyld="llm_spec_verify")
    return p.build()


def seed_spec_batched(model: Any, kv: PagedKVCollection,
                      QS: DictCollection, LIM: DictCollection,
                      DTOKS: DictCollection, seq: Any, token: int,
                      draft: Sequence[int], pad: int, *,
                      eos: int | None = None) -> int:
    """Seed ONE stream's batched-spec-superpool inputs AND pre-stage the
    draft chain's k/v into its tail slots (the speculative appends the
    pool's causal LIM masks make visible position by position, and
    ``rollback_tail`` scrubs on reject).  Callers must have run
    :func:`preallocate_decode_steps` first — the staged slots are
    private by then.  Returns the position count ``1 + len(draft)``.

    Tile contracts (change them HERE and in the kernels, nowhere
    else): ``QS(seq)`` ``(pad, 3, H, D)`` per-position q3 stacks;
    ``LIM(seq, p)`` ``(pad,)`` per-position valid-slot counts of page
    p; ``DTOKS(seq)`` ``(pad+2,)`` ``[n, eos, chain..., 0 pad]``."""
    chain = [int(token)] + [int(d) for d in draft]
    n = len(chain)
    if n > pad:
        raise ValueError(f"{n} positions exceed pad {pad}")
    P = kv.page_size
    L0 = kv.seq_len(seq)
    q3s = [model.q3(t) for t in chain]
    # pre-stage the appends, one disciplined host write per touched
    # page (update_page_host: sources the newest live copy — the tier
    # or a device copy may be ahead of host — then detaches accelerator
    # copies and jumps the host version past every one, so a deferred
    # device writeback can never clobber the staged draft k/v); the
    # boundary page's existing accepted slots are preserved
    by_page: dict[int, list[tuple[int, int]]] = {}
    for t in range(n):
        pg, slot = divmod(L0 + t, P)
        by_page.setdefault(pg, []).append((slot, t))
    for pg, entries in by_page.items():

        def stage(val: np.ndarray, _pg: int = pg,
                  _entries: list = entries) -> np.ndarray:
            for slot, t in _entries:
                val[0, slot] = q3s[t][1]
                val[1, slot] = q3s[t][2]
            val[META_CH, 0, 0, 0] = min(P, L0 + n - _pg * P)
            return val

        kv.update_page_host(seq, pg, stage)
    H, D = kv.num_heads, kv.head_dim
    qs = np.zeros((pad, 3, H, D), np.float32)
    for t in range(n):
        qs[t] = q3s[t]
    qc = QS.data_of(seq).get_copy(0)
    qc.value = qs
    qc.version += 1
    for p in range(_spec_attend_pages(L0, n, P)):
        lim = np.zeros(pad, np.float32)
        for t in range(n):
            lim[t] = max(0, min(L0 + t - p * P, P))
        lc = LIM.data_of(seq, p).get_copy(0)
        lc.value = lim
        lc.version += 1
    dt = np.zeros(pad + 2, np.float32)
    dt[0] = n
    dt[1] = -1.0 if eos is None else float(eos)
    dt[2:2 + n] = chain
    dc = DTOKS.data_of(seq).get_copy(0)
    dc.value = dt
    dc.version += 1
    return n


def seed_spec_batched_pool(model: Any, kv: PagedKVCollection,
                           QS: DictCollection, LIM: DictCollection,
                           DTOKS: DictCollection, EMB: DictCollection,
                           prompts: dict[Any, Sequence[int]],
                           drafts: dict[Any, Sequence[int]], *,
                           pad: int | None = None,
                           eos: int | None = None
                           ) -> tuple[dict[Any, int], int]:
    """Host-side prep making :func:`spec_batched_ptg`'s input contract
    executable with CALLER-CHOSEN drafts — the batched twin of
    :func:`seed_spec_superpool`, stated ONCE so the analysis sweep and
    the pool-level tests consume the same staging contract the batcher
    runs: prefill each prompt's pages in place, preallocate every
    position's write slot, stage the draft chains
    (:func:`seed_spec_batched`).  Returns ``(positions per seq, pad)``.
    """
    seed_emb_table(model, EMB)
    if pad is None:
        pad = max(len(d) for d in drafts.values()) + 1
    npos: dict[Any, int] = {}
    for seq, prompt in prompts.items():
        kv.alloc_seq(seq)
        for key, tile in prefill_chunks(model, kv, seq,
                                        prompt[:-1]).items():
            pg = kv.data_of(*key).get_copy(0)
            pg.value = np.array(tile, copy=True)
            pg.version += 1
        npos[seq] = 1 + len(drafts[seq])
        preallocate_decode_steps(kv, seq, npos[seq])
        seed_spec_batched(model, kv, QS, LIM, DTOKS, seq, prompt[-1],
                          drafts[seq], pad, eos=eos)
    return npos, pad


def read_spec_batched(VOUT: DictCollection, seq: Any
                      ) -> tuple[list[int], bool]:
    """Read one stream's batched-spec result: the accepted prefix's
    tokens (1..n per pool) and whether a LIVE position sampled EOS —
    a rejected or post-EOS token never surfaces."""
    v = np.asarray(VOUT.data_of(seq).newest_copy().value)
    m = int(round(float(v[0])))
    return [int(round(float(v[2 + i]))) for i in range(m)], v[1] > 0.5


def seed_spec_stream(model: Any, DRAFT: DictCollection,
                     DTOK: DictCollection, STOK: DictCollection,
                     seq: Any, token: int, draft: Sequence[int], *,
                     eos: int | None = None) -> int:
    """Seed ONE stream's speculative-superpool inputs: position 0's
    query is the real current ``token``, positions 1.. the drafter's
    proposals — ``DRAFT(seq, t)`` the q3 stacks, ``DTOK(seq, t)`` the
    token ids the VERIFY bodies compare, ``STOK(seq, -1)`` the
    ``[token, live=1, done=0, eos]`` accept-chain seed (``eos < 0`` =
    disabled).  Returns the position count (``1 + len(draft)``).  The
    layout contract lives HERE and in the kernel, nowhere else."""
    chain = [int(token)] + [int(d) for d in draft]
    for t, tok in enumerate(chain):
        dc = DRAFT.data_of(seq, t).get_copy(0)
        dc.value = model.q3(tok)
        dc.version += 1
        kc = DTOK.data_of(seq, t).get_copy(0)
        kc.value = np.array([float(tok)], np.float32)
        kc.version += 1
    sc = STOK.data_of(seq, -1).get_copy(0)
    sc.value = np.array([float(token), 1.0, 0.0,
                         -1.0 if eos is None else float(eos)],
                        np.float32)
    sc.version += 1
    return len(chain)


def read_spec_chain(STOK: DictCollection, seq: Any,
                    n: int) -> tuple[list[int], bool]:
    """Read a sequence's n-position VERIFY chain the way the batcher
    does: only LIVE positions' tokens surface (the first draft mismatch
    kills the chain; an EOS at a live position finishes the stream),
    so a rejected or post-EOS token can never reach a client.  Returns
    ``(tokens, done)``."""
    toks: list[int] = []
    done = False
    for t in range(n):
        v = np.asarray(STOK.data_of(seq, t).newest_copy().value)
        if v[1] > 0.5:
            toks.append(int(round(float(v[0]))))
            if v[2] > 0.5:
                done = True
    return toks, done


def seed_spec_superpool(model: Any, kv: PagedKVCollection,
                        DRAFT: DictCollection, DTOK: DictCollection,
                        STOK: DictCollection, EMB: DictCollection,
                        prompts: dict[Any, Sequence[int]],
                        drafts: dict[Any, Sequence[int]], *,
                        eos: int | None = None) -> dict[Any, int]:
    """Host-side prep making :func:`spec_superpool_ptg`'s input contract
    executable with CALLER-CHOSEN drafts (the acceptance rate is then
    exactly the drafts' correctness): prefill each prompt's pages in
    place, preallocate every position's write slot, seed the spec
    collections.  Returns the per-seq position counts.  Pool-level
    tests build on this instead of re-deriving the seeding contract."""
    seed_emb_table(model, EMB)
    npos: dict[Any, int] = {}
    for seq, prompt in prompts.items():
        kv.alloc_seq(seq)
        for key, tile in prefill_chunks(model, kv, seq,
                                        prompt[:-1]).items():
            pg = kv.data_of(*key).get_copy(0)
            pg.value = np.array(tile, copy=True)
            pg.version += 1
        npos[seq] = 1 + len(drafts[seq])
        preallocate_decode_steps(kv, seq, npos[seq])
        seed_spec_stream(model, DRAFT, DTOK, STOK, seq, prompt[-1],
                         drafts[seq], eos=eos)
    return npos


def prefill_chunks(model: Any, kv: PagedKVCollection, seq: Any,
                   tokens: Sequence[int]) -> dict[tuple, np.ndarray]:
    """Host-side prefill prep: allocate ``seq``'s pages for ``tokens``
    and return the ``(seq, chunk) -> tile`` map the T collection serves.
    Advances the length ledger — the PF tasks only move the bytes.

    Chunk indices continue from the sequence's CURRENT page count, so a
    prefix-cache adoptee (first ``m`` pages CoW-shared from the trie,
    ledger at the page boundary) prefills only its unmatched tail:
    ``tokens`` are then ``prompt[m * page_size:-1]`` and land in pages
    ``m, m+1, ...`` — a fresh sequence starts at chunk 0 unchanged."""
    P = kv.page_size
    chunks: dict[tuple, np.ndarray] = {}
    n = len(tokens)
    c0 = kv.npages(seq)
    for j in range((n + P - 1) // P):
        kv.alloc_page(seq)
        part = tokens[j * P:(j + 1) * P]
        tile = np.zeros(kv.default_dtt.shape, kv.dtype)
        for i, tok in enumerate(part):
            q3 = model.q3(tok)
            tile[0, i] = q3[1]
            tile[1, i] = q3[2]
        tile[META_CH, 0, 0, 0] = len(part)
        chunks[(seq, c0 + j)] = tile
    kv.note_appended(seq, n)
    return chunks


def seed_emb_table(model: Any, EMB: DictCollection) -> None:
    """Load ``EMB(0,)`` with the model's precomputed ``(V, 3, H, D)``
    q3 stack table — the tile the in-graph SAMPLE class computes logits
    and next-step queries from (one gather per token)."""
    ec = EMB.data_of(0).get_copy(0)
    ec.value = np.array(model.q3_table(), copy=True)
    ec.version += 1


def seed_stream_step(model: Any, Q: DictCollection, TOK: DictCollection,
                     seq: Any, token: int, *,
                     eos: int | None = None) -> None:
    """Seed ONE stream's per-iteration inputs: ``Q(seq)`` with the
    current token's q3 stack and ``TOK(seq, -1)`` with the
    ``[token, done=0, eos]`` chain-seed tile (``eos < 0`` = disabled) —
    the layout contract the SAMPLE bodies read.  The batcher calls this
    per stream per superpool; if the layout changes, it changes HERE
    and in the kernel, nowhere else."""
    qc = Q.data_of(seq).get_copy(0)
    qc.value = model.q3(token)
    qc.version += 1
    t0 = TOK.data_of(seq, -1).get_copy(0)
    t0.value = np.array([float(token), 0.0,
                         -1.0 if eos is None else float(eos)],
                        np.float32)
    t0.version += 1


def seed_decode_superpool(model: Any, kv: PagedKVCollection,
                          Q: DictCollection, TOK: DictCollection,
                          EMB: DictCollection,
                          prompts: dict[Any, Sequence[int]],
                          steps: dict[Any, int], *,
                          eos: int | None = None) -> None:
    """Host-side prep that makes :func:`decode_superpool_ptg`'s input
    contract executable: prefill each prompt's pages in place (no
    runtime), preallocate every step's write slot, and seed the
    collections through the same :func:`seed_emb_table` /
    :func:`seed_stream_step` the batcher uses.  Pool-level tests build
    on this instead of re-deriving the seeding contract."""
    seed_emb_table(model, EMB)
    for seq, prompt in prompts.items():
        kv.alloc_seq(seq)
        for key, tile in prefill_chunks(model, kv, seq,
                                        prompt[:-1]).items():
            pg = kv.data_of(*key).get_copy(0)
            pg.value = np.array(tile, copy=True)
            pg.version += 1
        preallocate_decode_steps(kv, seq, steps[seq])
        seed_stream_step(model, Q, TOK, seq, prompt[-1], eos=eos)


def read_token_chain(TOK: DictCollection, seq: Any,
                     k: int) -> tuple[list[int], bool]:
    """Read a sequence's k-step TOK chain the way the batcher does:
    tokens past the step whose done flag fired are the predicated tail
    and are never surfaced.  Returns ``(tokens, done)`` — ``done`` is
    the last surfaced step's flag, so an EOS on the final step still
    reads as finished."""
    toks: list[int] = []
    done = False
    for t in range(k):
        v = np.asarray(TOK.data_of(seq, t).newest_copy().value)
        if not done:
            toks.append(int(round(float(v[0]))))
            done = bool(v[1] > 0.5)
    return toks, done
